-- define [SDATE] = rand_date(1998, 2002)
WITH ssr AS (
  SELECT s_store_id AS store_id,
         SUM(ss_ext_sales_price) AS sales,
         SUM(COALESCE(sr_return_amt, 0)) AS returns_amt,
         SUM(ss_net_profit - COALESCE(sr_net_loss, 0)) AS profit
  FROM store_sales
       LEFT OUTER JOIN store_returns ON
           (ss_item_sk = sr_item_sk AND ss_ticket_number = sr_ticket_number),
       date_dim, store, item, promotion
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                   AND (CAST('[SDATE]' AS DATE) + INTERVAL 30 DAYS)
    AND ss_store_sk = s_store_sk
    AND ss_item_sk = i_item_sk
    AND i_current_price > 50
    AND ss_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY s_store_id
),
csr AS (
  SELECT cp_catalog_page_id AS catalog_page_id,
         SUM(cs_ext_sales_price) AS sales,
         SUM(COALESCE(cr_return_amount, 0)) AS returns_amt,
         SUM(cs_net_profit - COALESCE(cr_net_loss, 0)) AS profit
  FROM catalog_sales
       LEFT OUTER JOIN catalog_returns ON
           (cs_item_sk = cr_item_sk AND cs_order_number = cr_order_number),
       date_dim, catalog_page, item, promotion
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                   AND (CAST('[SDATE]' AS DATE) + INTERVAL 30 DAYS)
    AND cs_catalog_page_sk = cp_catalog_page_sk
    AND cs_item_sk = i_item_sk
    AND i_current_price > 50
    AND cs_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY cp_catalog_page_id
),
wsr AS (
  SELECT web_site_id,
         SUM(ws_ext_sales_price) AS sales,
         SUM(COALESCE(wr_return_amt, 0)) AS returns_amt,
         SUM(ws_net_profit - COALESCE(wr_net_loss, 0)) AS profit
  FROM web_sales
       LEFT OUTER JOIN web_returns ON
           (ws_item_sk = wr_item_sk AND ws_order_number = wr_order_number),
       date_dim, web_site, item, promotion
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                   AND (CAST('[SDATE]' AS DATE) + INTERVAL 30 DAYS)
    AND ws_web_site_sk = web_site_sk
    AND ws_item_sk = i_item_sk
    AND i_current_price > 50
    AND ws_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY web_site_id
)
SELECT channel, id, SUM(sales) AS sales, SUM(returns_amt) AS returns_amt,
       SUM(profit) AS profit
FROM (SELECT 'store channel' AS channel,
             CONCAT('store', store_id) AS id, sales, returns_amt, profit
      FROM ssr
      UNION ALL
      SELECT 'catalog channel' AS channel,
             CONCAT('catalog_page', catalog_page_id) AS id, sales,
             returns_amt, profit
      FROM csr
      UNION ALL
      SELECT 'web channel' AS channel,
             CONCAT('web_site', web_site_id) AS id, sales, returns_amt,
             profit
      FROM wsr) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
