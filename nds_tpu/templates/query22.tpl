-- define [DMS] = uniform_int(1176, 1224)
SELECT i_product_name, i_brand, i_class, i_category,
       AVG(inv_quantity_on_hand) AS qoh
FROM inventory, date_dim, item
WHERE inv_date_sk = d_date_sk
  AND inv_item_sk = i_item_sk
  AND d_month_seq BETWEEN [DMS] AND [DMS] + 11
GROUP BY ROLLUP (i_product_name, i_brand, i_class, i_category)
ORDER BY qoh, i_product_name, i_brand, i_class, i_category
LIMIT 100
