-- define [YEAR] = uniform_int(1998, 2002)
-- define [STATES] = choice_n(8, 'AL','AK','AZ','CA','CO','FL','GA','IA','IL','IN','KS','KY','LA','MI','MN','MO','MS','NC')
SELECT SUM(ss_net_profit) / SUM(ss_ext_sales_price) AS gross_margin,
       i_category, i_class,
       GROUPING(i_category) + GROUPING(i_class) AS lochierarchy,
       RANK() OVER (PARTITION BY GROUPING(i_category) + GROUPING(i_class),
                                 CASE WHEN GROUPING(i_class) = 0
                                      THEN i_category END
                    ORDER BY SUM(ss_net_profit) / SUM(ss_ext_sales_price)
                        ASC) AS rank_within_parent
FROM store_sales, date_dim d1, item, store
WHERE d1.d_year = [YEAR]
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND s_state IN ([STATES])
GROUP BY ROLLUP (i_category, i_class)
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN i_category END,
         rank_within_parent
LIMIT 100
