-- define [YEAR] = uniform_int(1998, 2002)
-- define [MONTH] = uniform_int(1, 4)
WITH inv AS (
  SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy, stdev, mean,
         CASE WHEN mean = 0 THEN NULL ELSE stdev / mean END AS cov
  FROM (SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
               STDDEV_SAMP(inv_quantity_on_hand) AS stdev,
               AVG(inv_quantity_on_hand) AS mean
        FROM inventory, item, warehouse, date_dim
        WHERE inv_item_sk = i_item_sk
          AND inv_warehouse_sk = w_warehouse_sk
          AND inv_date_sk = d_date_sk
          AND d_year = [YEAR]
        GROUP BY w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy) foo
  WHERE CASE WHEN mean = 0 THEN 0 ELSE stdev / mean END > 1
)
SELECT inv1.w_warehouse_sk AS wsk1, inv1.i_item_sk AS isk1,
       inv1.d_moy AS moy1, inv1.mean AS mean1, inv1.cov AS cov1,
       inv2.w_warehouse_sk AS wsk2, inv2.i_item_sk AS isk2,
       inv2.d_moy AS moy2, inv2.mean AS mean2, inv2.cov AS cov2
FROM inv inv1, inv inv2
WHERE inv1.i_item_sk = inv2.i_item_sk
  AND inv1.w_warehouse_sk = inv2.w_warehouse_sk
  AND inv1.d_moy = [MONTH]
  AND inv2.d_moy = [MONTH] + 1
ORDER BY inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean,
         inv1.cov, inv2.d_moy, inv2.mean, inv2.cov;
WITH inv AS (
  SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy, stdev, mean,
         CASE WHEN mean = 0 THEN NULL ELSE stdev / mean END AS cov
  FROM (SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
               STDDEV_SAMP(inv_quantity_on_hand) AS stdev,
               AVG(inv_quantity_on_hand) AS mean
        FROM inventory, item, warehouse, date_dim
        WHERE inv_item_sk = i_item_sk
          AND inv_warehouse_sk = w_warehouse_sk
          AND inv_date_sk = d_date_sk
          AND d_year = [YEAR]
        GROUP BY w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy) foo
  WHERE CASE WHEN mean = 0 THEN 0 ELSE stdev / mean END > 1
)
SELECT inv1.w_warehouse_sk AS wsk1, inv1.i_item_sk AS isk1,
       inv1.d_moy AS moy1, inv1.mean AS mean1, inv1.cov AS cov1,
       inv2.w_warehouse_sk AS wsk2, inv2.i_item_sk AS isk2,
       inv2.d_moy AS moy2, inv2.mean AS mean2, inv2.cov AS cov2
FROM inv inv1, inv inv2
WHERE inv1.i_item_sk = inv2.i_item_sk
  AND inv1.w_warehouse_sk = inv2.w_warehouse_sk
  AND inv1.d_moy = [MONTH]
  AND inv2.d_moy = [MONTH] + 1
  AND inv1.cov > 1.5
ORDER BY inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean,
         inv1.cov, inv2.d_moy, inv2.mean, inv2.cov
