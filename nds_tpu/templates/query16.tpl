-- define [DATE] = rand_date(1999, 2002)
-- define [STATE] = choice('GA','ID','IL','IN','IA','KS','KY','LA')
-- define [COUNTIES] = choice_n(5, 'Williamson County','Walker County','Ziebach County','Daviess County','Barrow County','Franklin Parish','Luce County','Richland County','Furnas County','Maverick County')
SELECT COUNT(DISTINCT cs_order_number) AS order_count,
       SUM(cs_ext_ship_cost) AS total_shipping_cost,
       SUM(cs_net_profit) AS total_net_profit
FROM catalog_sales cs1, date_dim, customer_address, call_center
WHERE d_date BETWEEN CAST('[DATE]' AS DATE)
                 AND (CAST('[DATE]' AS DATE) + INTERVAL 60 DAYS)
  AND cs1.cs_ship_date_sk = d_date_sk
  AND cs1.cs_ship_addr_sk = ca_address_sk
  AND ca_state = '[STATE]'
  AND cs1.cs_call_center_sk = cc_call_center_sk
  AND cc_county IN ([COUNTIES])
  AND EXISTS (SELECT *
              FROM catalog_sales cs2
              WHERE cs1.cs_order_number = cs2.cs_order_number
                AND cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  AND NOT EXISTS (SELECT *
                  FROM catalog_returns cr1
                  WHERE cs1.cs_order_number = cr1.cr_order_number)
ORDER BY order_count
LIMIT 100
