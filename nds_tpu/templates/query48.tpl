-- define [YEAR] = uniform_int(1998, 2002)
-- define [MS] = choice('S','M','D','W','U')
-- define [ES] = choice('Primary','Secondary','College','2 yr Degree','4 yr Degree','Advanced Degree','Unknown')
SELECT SUM(ss_quantity) AS total_quantity
FROM store_sales, store, customer_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_year = [YEAR]
  AND cd_demo_sk = ss_cdemo_sk
  AND ((cd_marital_status = '[MS]'
        AND cd_education_status = '[ES]'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
       OR (cd_marital_status = 'S'
           AND cd_education_status = 'College'
           AND ss_sales_price BETWEEN 50.00 AND 100.00)
       OR (cd_marital_status = 'W'
           AND cd_education_status = '2 yr Degree'
           AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ss_addr_sk = ca_address_sk
  AND ca_country = 'United States'
  AND ((ca_state IN ('CO', 'OH', 'TX')
        AND ss_net_profit BETWEEN 0 AND 2000)
       OR (ca_state IN ('OR', 'MN', 'KY')
           AND ss_net_profit BETWEEN 150 AND 3000)
       OR (ca_state IN ('VA', 'CA', 'MS')
           AND ss_net_profit BETWEEN 50 AND 25000))
