-- define [YEAR] = uniform_int(1998, 2002)
SELECT s_store_name, s_store_id,
       SUM(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price ELSE NULL END) AS sun_sales,
       SUM(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price ELSE NULL END) AS mon_sales,
       SUM(CASE WHEN d_day_name = 'Tuesday' THEN ss_sales_price ELSE NULL END) AS tue_sales,
       SUM(CASE WHEN d_day_name = 'Wednesday' THEN ss_sales_price ELSE NULL END) AS wed_sales,
       SUM(CASE WHEN d_day_name = 'Thursday' THEN ss_sales_price ELSE NULL END) AS thu_sales,
       SUM(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price ELSE NULL END) AS fri_sales,
       SUM(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price ELSE NULL END) AS sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk
  AND s_store_sk = ss_store_sk
  AND s_gmt_offset = -5
  AND d_year = [YEAR]
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
         wed_sales, thu_sales, fri_sales, sat_sales
LIMIT 100
