-- define [YEAR] = uniform_int(1998, 2002)
-- define [STATE] = choice('TN','SC','GA','AL','KY','VA','NC','TX','OH','MI')
WITH customer_total_return AS (
  SELECT sr_customer_sk AS ctr_customer_sk,
         sr_store_sk AS ctr_store_sk,
         SUM(sr_return_amt) AS ctr_total_return
  FROM store_returns, date_dim
  WHERE sr_returned_date_sk = d_date_sk AND d_year = [YEAR]
  GROUP BY sr_customer_sk, sr_store_sk
)
SELECT c_customer_id
FROM customer_total_return ctr1, store, customer
WHERE ctr1.ctr_total_return >
      (SELECT AVG(ctr_total_return) * 1.2
       FROM customer_total_return ctr2
       WHERE ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  AND s_store_sk = ctr1.ctr_store_sk
  AND s_state = '[STATE]'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id
LIMIT 100
