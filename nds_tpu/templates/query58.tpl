-- define [SDATE] = rand_date(1998, 2002)
WITH ss_items AS (
  SELECT i_item_id AS item_id, SUM(ss_ext_sales_price) AS ss_item_rev
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND d_date IN (SELECT d_date
                   FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq
                                       FROM date_dim
                                       WHERE d_date = CAST('[SDATE]' AS DATE)))
    AND ss_sold_date_sk = d_date_sk
  GROUP BY i_item_id
),
cs_items AS (
  SELECT i_item_id AS item_id, SUM(cs_ext_sales_price) AS cs_item_rev
  FROM catalog_sales, item, date_dim
  WHERE cs_item_sk = i_item_sk
    AND d_date IN (SELECT d_date
                   FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq
                                       FROM date_dim
                                       WHERE d_date = CAST('[SDATE]' AS DATE)))
    AND cs_sold_date_sk = d_date_sk
  GROUP BY i_item_id
),
ws_items AS (
  SELECT i_item_id AS item_id, SUM(ws_ext_sales_price) AS ws_item_rev
  FROM web_sales, item, date_dim
  WHERE ws_item_sk = i_item_sk
    AND d_date IN (SELECT d_date
                   FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq
                                       FROM date_dim
                                       WHERE d_date = CAST('[SDATE]' AS DATE)))
    AND ws_sold_date_sk = d_date_sk
  GROUP BY i_item_id
)
SELECT ss_items.item_id,
       ss_item_rev,
       ss_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
           AS ss_dev,
       cs_item_rev,
       cs_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
           AS cs_dev,
       ws_item_rev,
       ws_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
           AS ws_dev,
       (ss_item_rev + cs_item_rev + ws_item_rev) / 3 AS average
FROM ss_items, cs_items, ws_items
WHERE ss_items.item_id = cs_items.item_id
  AND ss_items.item_id = ws_items.item_id
  AND ss_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
  AND ss_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND cs_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND cs_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND ws_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND ws_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
ORDER BY ss_items.item_id, ss_item_rev
LIMIT 100
