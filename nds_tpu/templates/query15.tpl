-- define [YEAR] = uniform_int(1998, 2002)
-- define [QOY] = uniform_int(1, 2)
SELECT ca_zip, SUM(cs_sales_price) AS total_sales
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND (SUBSTR(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405', '86475',
                                '85392', '85460', '80348', '81792')
       OR ca_state IN ('CA', 'WA', 'GA')
       OR cs_sales_price > 500)
  AND cs_sold_date_sk = d_date_sk
  AND d_qoy = [QOY]
  AND d_year = [YEAR]
GROUP BY ca_zip
ORDER BY ca_zip
LIMIT 100
