-- define [YEAR] = uniform_int(1999, 2002)
-- define [MONTH] = uniform_int(1, 4)
-- define [STATES] = choice_n(3, 'AL','AK','AZ','CA','CO','FL','GA','IA','IL','IN','KS','KY','LA','MI','MN','MO')
SELECT cd_gender, cd_marital_status, cd_education_status, COUNT(*) AS cnt1,
       cd_purchase_estimate, COUNT(*) AS cnt2, cd_credit_rating,
       COUNT(*) AS cnt3
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_state IN ([STATES])
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT *
              FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = [YEAR]
                AND d_moy BETWEEN [MONTH] AND [MONTH] + 2)
  AND NOT EXISTS (SELECT *
                  FROM web_sales, date_dim
                  WHERE c.c_customer_sk = ws_bill_customer_sk
                    AND ws_sold_date_sk = d_date_sk
                    AND d_year = [YEAR]
                    AND d_moy BETWEEN [MONTH] AND [MONTH] + 2)
  AND NOT EXISTS (SELECT *
                  FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = [YEAR]
                    AND d_moy BETWEEN [MONTH] AND [MONTH] + 2)
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
LIMIT 100
