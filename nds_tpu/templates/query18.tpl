-- define [YEAR] = uniform_int(1998, 2002)
-- define [GEN] = choice('M', 'F')
-- define [ES] = choice('Primary','Secondary','College','2 yr Degree','4 yr Degree','Advanced Degree','Unknown')
-- define [MONTHS] = choice_n(6, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12)
-- define [STATES] = choice_n(7, 'AL','CA','CO','FL','GA','IA','IL','IN','KS','KY','LA','MI','MN','MO','MS','NC','ND')
SELECT i_item_id, ca_country, ca_state, ca_county,
       AVG(CAST(cs_quantity AS DOUBLE)) AS agg1,
       AVG(CAST(cs_list_price AS DOUBLE)) AS agg2,
       AVG(CAST(cs_coupon_amt AS DOUBLE)) AS agg3,
       AVG(CAST(cs_sales_price AS DOUBLE)) AS agg4,
       AVG(CAST(cs_net_profit AS DOUBLE)) AS agg5,
       AVG(CAST(c_birth_year AS DOUBLE)) AS agg6,
       AVG(CAST(cd1.cd_dep_count AS DOUBLE)) AS agg7
FROM catalog_sales, customer_demographics cd1, customer_demographics cd2,
     customer, customer_address, date_dim, item
WHERE cs_sold_date_sk = d_date_sk
  AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd1.cd_demo_sk
  AND cs_bill_customer_sk = c_customer_sk
  AND cd1.cd_gender = '[GEN]'
  AND cd1.cd_education_status = '[ES]'
  AND c_current_cdemo_sk = cd2.cd_demo_sk
  AND c_current_addr_sk = ca_address_sk
  AND c_birth_month IN ([MONTHS])
  AND d_year = [YEAR]
  AND ca_state IN ([STATES])
GROUP BY ROLLUP (i_item_id, ca_country, ca_state, ca_county)
ORDER BY ca_country, ca_state, ca_county, i_item_id
LIMIT 100
