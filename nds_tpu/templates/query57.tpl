-- define [YEAR] = uniform_int(1999, 2001)
WITH v1 AS (
  SELECT i_category, i_brand, cc_name, d_year, d_moy,
         SUM(cs_sales_price) AS sum_sales,
         AVG(SUM(cs_sales_price)) OVER
             (PARTITION BY i_category, i_brand, cc_name, d_year)
             AS avg_monthly_sales,
         RANK() OVER
             (PARTITION BY i_category, i_brand, cc_name
              ORDER BY d_year, d_moy) AS rn
  FROM item, catalog_sales, date_dim, call_center
  WHERE cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND cc_call_center_sk = cs_call_center_sk
    AND (d_year = [YEAR]
         OR (d_year = [YEAR] - 1 AND d_moy = 12)
         OR (d_year = [YEAR] + 1 AND d_moy = 1))
  GROUP BY i_category, i_brand, cc_name, d_year, d_moy
),
v2 AS (
  SELECT v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
         v1.avg_monthly_sales, v1.sum_sales,
         v1_lag.sum_sales AS psum, v1_lead.sum_sales AS nsum
  FROM v1, v1 v1_lag, v1 v1_lead
  WHERE v1.i_category = v1_lag.i_category
    AND v1.i_category = v1_lead.i_category
    AND v1.i_brand = v1_lag.i_brand
    AND v1.i_brand = v1_lead.i_brand
    AND v1.cc_name = v1_lag.cc_name
    AND v1.cc_name = v1_lead.cc_name
    AND v1.rn = v1_lag.rn + 1
    AND v1.rn = v1_lead.rn - 1
)
SELECT *
FROM v2
WHERE d_year = [YEAR]
  AND avg_monthly_sales > 0
  AND CASE WHEN avg_monthly_sales > 0
           THEN ABS(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, cc_name
LIMIT 100
