-- define [RC1] = uniform_int(20000, 80000)
-- define [RC2] = uniform_int(15000, 60000)
-- define [RC3] = uniform_int(10000, 50000)
-- define [RC4] = uniform_int(5000, 40000)
-- define [RC5] = uniform_int(1000, 30000)
SELECT CASE WHEN (SELECT COUNT(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) > [RC1]
            THEN (SELECT AVG(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20)
            ELSE (SELECT AVG(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) END AS bucket1,
       CASE WHEN (SELECT COUNT(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) > [RC2]
            THEN (SELECT AVG(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40)
            ELSE (SELECT AVG(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) END AS bucket2,
       CASE WHEN (SELECT COUNT(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) > [RC3]
            THEN (SELECT AVG(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60)
            ELSE (SELECT AVG(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) END AS bucket3,
       CASE WHEN (SELECT COUNT(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80) > [RC4]
            THEN (SELECT AVG(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80)
            ELSE (SELECT AVG(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80) END AS bucket4,
       CASE WHEN (SELECT COUNT(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100) > [RC5]
            THEN (SELECT AVG(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100)
            ELSE (SELECT AVG(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100) END AS bucket5
FROM reason
WHERE r_reason_sk = 1
