-- define [YEAR] = uniform_int(1998, 2000)
-- define [MONTH] = uniform_int(1, 7)
WITH frequent_ss_items AS (
  SELECT SUBSTR(i_item_desc, 1, 30) AS itemdesc, i_item_sk AS item_sk,
         d_date AS solddate, COUNT(*) AS cnt
  FROM store_sales, date_dim, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_item_sk = i_item_sk
    AND d_year IN ([YEAR], [YEAR] + 1, [YEAR] + 2, [YEAR] + 3)
  GROUP BY SUBSTR(i_item_desc, 1, 30), i_item_sk, d_date
  HAVING COUNT(*) > 4
),
max_store_sales AS (
  SELECT MAX(csales) AS tpcds_cmax
  FROM (SELECT c_customer_sk, SUM(ss_quantity * ss_sales_price) AS csales
        FROM store_sales, customer, date_dim
        WHERE ss_customer_sk = c_customer_sk
          AND ss_sold_date_sk = d_date_sk
          AND d_year IN ([YEAR], [YEAR] + 1, [YEAR] + 2, [YEAR] + 3)
        GROUP BY c_customer_sk) t
),
best_ss_customer AS (
  SELECT c_customer_sk, SUM(ss_quantity * ss_sales_price) AS ssales
  FROM store_sales, customer
  WHERE ss_customer_sk = c_customer_sk
  GROUP BY c_customer_sk
  HAVING SUM(ss_quantity * ss_sales_price) >
         0.95 * (SELECT tpcds_cmax FROM max_store_sales)
)
SELECT SUM(sales) AS total_sales
FROM (SELECT cs_quantity * cs_list_price AS sales
      FROM catalog_sales, date_dim
      WHERE d_year = [YEAR]
        AND d_moy = [MONTH]
        AND cs_sold_date_sk = d_date_sk
        AND cs_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND cs_bill_customer_sk IN (SELECT c_customer_sk
                                    FROM best_ss_customer)
      UNION ALL
      SELECT ws_quantity * ws_list_price AS sales
      FROM web_sales, date_dim
      WHERE d_year = [YEAR]
        AND d_moy = [MONTH]
        AND ws_sold_date_sk = d_date_sk
        AND ws_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND ws_bill_customer_sk IN (SELECT c_customer_sk
                                    FROM best_ss_customer)) x
LIMIT 100;
WITH frequent_ss_items AS (
  SELECT SUBSTR(i_item_desc, 1, 30) AS itemdesc, i_item_sk AS item_sk,
         d_date AS solddate, COUNT(*) AS cnt
  FROM store_sales, date_dim, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_item_sk = i_item_sk
    AND d_year IN ([YEAR], [YEAR] + 1, [YEAR] + 2, [YEAR] + 3)
  GROUP BY SUBSTR(i_item_desc, 1, 30), i_item_sk, d_date
  HAVING COUNT(*) > 4
),
max_store_sales AS (
  SELECT MAX(csales) AS tpcds_cmax
  FROM (SELECT c_customer_sk, SUM(ss_quantity * ss_sales_price) AS csales
        FROM store_sales, customer, date_dim
        WHERE ss_customer_sk = c_customer_sk
          AND ss_sold_date_sk = d_date_sk
          AND d_year IN ([YEAR], [YEAR] + 1, [YEAR] + 2, [YEAR] + 3)
        GROUP BY c_customer_sk) t
),
best_ss_customer AS (
  SELECT c_customer_sk, SUM(ss_quantity * ss_sales_price) AS ssales
  FROM store_sales, customer
  WHERE ss_customer_sk = c_customer_sk
  GROUP BY c_customer_sk
  HAVING SUM(ss_quantity * ss_sales_price) >
         0.95 * (SELECT tpcds_cmax FROM max_store_sales)
)
SELECT c_last_name, c_first_name, sales
FROM (SELECT c_last_name, c_first_name,
             SUM(cs_quantity * cs_list_price) AS sales
      FROM catalog_sales, customer, date_dim
      WHERE d_year = [YEAR]
        AND d_moy = [MONTH]
        AND cs_sold_date_sk = d_date_sk
        AND cs_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND cs_bill_customer_sk IN (SELECT c_customer_sk
                                    FROM best_ss_customer)
        AND cs_bill_customer_sk = c_customer_sk
      GROUP BY c_last_name, c_first_name
      UNION ALL
      SELECT c_last_name, c_first_name,
             SUM(ws_quantity * ws_list_price) AS sales
      FROM web_sales, customer, date_dim
      WHERE d_year = [YEAR]
        AND d_moy = [MONTH]
        AND ws_sold_date_sk = d_date_sk
        AND ws_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND ws_bill_customer_sk IN (SELECT c_customer_sk
                                    FROM best_ss_customer)
        AND ws_bill_customer_sk = c_customer_sk
      GROUP BY c_last_name, c_first_name) y
ORDER BY c_last_name, c_first_name, sales
LIMIT 100
