-- define [YEAR] = uniform_int(1998, 2002)
-- define [GEN] = choice('M', 'F')
-- define [MS] = choice('S','M','D','W','U')
-- define [ES] = choice('Primary','Secondary','College','2 yr Degree','4 yr Degree','Advanced Degree','Unknown')
-- define [STATES] = choice_n(6, 'AL','AK','AZ','CA','CO','FL','GA','IA','IL','IN','KS','KY','LA','MI','MN','MO')
SELECT i_item_id, s_state, GROUPING(s_state) AS g_state,
       AVG(ss_quantity) AS agg1,
       AVG(ss_list_price) AS agg2,
       AVG(ss_coupon_amt) AS agg3,
       AVG(ss_sales_price) AS agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND cd_gender = '[GEN]'
  AND cd_marital_status = '[MS]'
  AND cd_education_status = '[ES]'
  AND d_year = [YEAR]
  AND s_state IN ([STATES])
GROUP BY ROLLUP (i_item_id, s_state)
ORDER BY i_item_id, s_state
LIMIT 100
