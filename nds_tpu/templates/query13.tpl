-- define [YEAR] = uniform_int(1998, 2002)
-- define [MS1] = choice('S','M','D','W','U')
-- define [MS2] = choice('S','M','D','W','U')
-- define [MS3] = choice('S','M','D','W','U')
-- define [ES1] = choice('Primary','Secondary','College','2 yr Degree','4 yr Degree')
-- define [ES2] = choice('Primary','Secondary','College','2 yr Degree','4 yr Degree')
-- define [ES3] = choice('Primary','Secondary','College','2 yr Degree','4 yr Degree')
-- define [STATES1] = choice_n(3, 'TN','SC','GA','AL','KY','VA','NC','TX','OH','MI')
-- define [STATES2] = choice_n(3, 'IL','IN','IA','KS','MO','NE','MN','WI','AR','OK')
-- define [STATES3] = choice_n(3, 'CA','OR','WA','NV','AZ','NM','UT','CO','ID','MT')
SELECT AVG(ss_quantity) AS avg_qty,
       AVG(ss_ext_sales_price) AS avg_esp,
       AVG(ss_ext_wholesale_cost) AS avg_ewc,
       SUM(ss_ext_wholesale_cost) AS sum_ewc
FROM store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = [YEAR]
  AND ((ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = '[MS1]'
        AND cd_education_status = '[ES1]'
        AND ss_sales_price BETWEEN 100.00 AND 150.00
        AND hd_dep_count = 3)
    OR (ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = '[MS2]'
        AND cd_education_status = '[ES2]'
        AND ss_sales_price BETWEEN 50.00 AND 100.00
        AND hd_dep_count = 1)
    OR (ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = '[MS3]'
        AND cd_education_status = '[ES3]'
        AND ss_sales_price BETWEEN 150.00 AND 200.00
        AND hd_dep_count = 1))
  AND ((ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ([STATES1])
        AND ss_net_profit BETWEEN 100 AND 200)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ([STATES2])
        AND ss_net_profit BETWEEN 150 AND 300)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ([STATES3])
        AND ss_net_profit BETWEEN 50 AND 250))
