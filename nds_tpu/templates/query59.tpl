-- define [DMS] = uniform_int(1176, 1212)
WITH wss AS (
  SELECT d_week_seq, ss_store_sk,
         SUM(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price ELSE NULL END) AS sun_sales,
         SUM(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price ELSE NULL END) AS mon_sales,
         SUM(CASE WHEN d_day_name = 'Tuesday' THEN ss_sales_price ELSE NULL END) AS tue_sales,
         SUM(CASE WHEN d_day_name = 'Wednesday' THEN ss_sales_price ELSE NULL END) AS wed_sales,
         SUM(CASE WHEN d_day_name = 'Thursday' THEN ss_sales_price ELSE NULL END) AS thu_sales,
         SUM(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price ELSE NULL END) AS fri_sales,
         SUM(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price ELSE NULL END) AS sat_sales
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk
  GROUP BY d_week_seq, ss_store_sk
)
SELECT s_store_name1, s_store_id1, d_week_seq1,
       sun_sales1 / sun_sales2 AS sun_ratio,
       mon_sales1 / mon_sales2 AS mon_ratio,
       tue_sales1 / tue_sales2 AS tue_ratio,
       wed_sales1 / wed_sales2 AS wed_ratio,
       thu_sales1 / thu_sales2 AS thu_ratio,
       fri_sales1 / fri_sales2 AS fri_ratio,
       sat_sales1 / sat_sales2 AS sat_ratio
FROM (SELECT s_store_name AS s_store_name1, wss.d_week_seq AS d_week_seq1,
             s_store_id AS s_store_id1, sun_sales AS sun_sales1,
             mon_sales AS mon_sales1, tue_sales AS tue_sales1,
             wed_sales AS wed_sales1, thu_sales AS thu_sales1,
             fri_sales AS fri_sales1, sat_sales AS sat_sales1
      FROM wss, store, date_dim d
      WHERE d.d_week_seq = wss.d_week_seq
        AND ss_store_sk = s_store_sk
        AND d_month_seq BETWEEN [DMS] AND [DMS] + 11) y,
     (SELECT s_store_name AS s_store_name2, wss.d_week_seq AS d_week_seq2,
             s_store_id AS s_store_id2, sun_sales AS sun_sales2,
             mon_sales AS mon_sales2, tue_sales AS tue_sales2,
             wed_sales AS wed_sales2, thu_sales AS thu_sales2,
             fri_sales AS fri_sales2, sat_sales AS sat_sales2
      FROM wss, store, date_dim d
      WHERE d.d_week_seq = wss.d_week_seq
        AND ss_store_sk = s_store_sk
        AND d_month_seq BETWEEN [DMS] + 12 AND [DMS] + 23) x
WHERE s_store_id1 = s_store_id2
  AND d_week_seq1 = d_week_seq2 - 52
ORDER BY s_store_name1, s_store_id1, d_week_seq1
LIMIT 100
