-- define [YEAR] = uniform_int(1999, 2002)
-- define [MONTH] = uniform_int(1, 4)
-- define [COUNTIES] = choice_n(5, 'Williamson County','Walker County','Ziebach County','Daviess County','Barrow County','Franklin Parish','Luce County','Richland County','Furnas County','Maverick County')
SELECT cd_gender, cd_marital_status, cd_education_status, COUNT(*) AS cnt1,
       cd_purchase_estimate, COUNT(*) AS cnt2, cd_credit_rating,
       COUNT(*) AS cnt3, cd_dep_count, COUNT(*) AS cnt4,
       cd_dep_employed_count, COUNT(*) AS cnt5, cd_dep_college_count,
       COUNT(*) AS cnt6
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_county IN ([COUNTIES])
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT *
              FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = [YEAR]
                AND d_moy BETWEEN [MONTH] AND [MONTH] + 3)
  AND (EXISTS (SELECT *
               FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk
                 AND d_year = [YEAR]
                 AND d_moy BETWEEN [MONTH] AND [MONTH] + 3)
       OR EXISTS (SELECT *
                  FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = [YEAR]
                    AND d_moy BETWEEN [MONTH] AND [MONTH] + 3))
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
LIMIT 100
