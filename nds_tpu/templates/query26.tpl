-- define [YEAR] = uniform_int(1998, 2002)
-- define [GEN] = choice('M','F')
-- define [MS] = choice('S','M','D','W','U')
-- define [ES] = choice('Primary','Secondary','College','2 yr Degree','4 yr Degree','Advanced Degree','Unknown')
SELECT i_item_id,
       AVG(cs_quantity) AS agg1,
       AVG(cs_list_price) AS agg2,
       AVG(cs_coupon_amt) AS agg3,
       AVG(cs_sales_price) AS agg4
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk
  AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd_demo_sk
  AND cs_promo_sk = p_promo_sk
  AND cd_gender = '[GEN]'
  AND cd_marital_status = '[MS]'
  AND cd_education_status = '[ES]'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = [YEAR]
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
