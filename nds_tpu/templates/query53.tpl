-- define [DMS] = uniform_int(1176, 1224)
SELECT *
FROM (SELECT i_manufact_id,
             SUM(ss_sales_price) AS sum_sales,
             AVG(SUM(ss_sales_price)) OVER (PARTITION BY i_manufact_id)
                 AS avg_quarterly_sales
      FROM item, store_sales, date_dim, store
      WHERE ss_item_sk = i_item_sk
        AND ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND d_month_seq IN ([DMS], [DMS] + 1, [DMS] + 2, [DMS] + 3,
                            [DMS] + 4, [DMS] + 5, [DMS] + 6, [DMS] + 7,
                            [DMS] + 8, [DMS] + 9, [DMS] + 10, [DMS] + 11)
        AND ((i_category IN ('Books', 'Children', 'Electronics')
              AND i_class IN ('personal', 'portable', 'reference', 'self-help')
              AND i_brand IN ('corpbrand #1', 'corpbrand #4',
                              'importbrand #9', 'corpbrand #9'))
             OR (i_category IN ('Women', 'Music', 'Men')
                 AND i_class IN ('accessories', 'classical',
                                 'fragrances', 'pants')
                 AND i_brand IN ('importbrand #1', 'corpbrand #2',
                                 'importbrand #3', 'importbrand #7')))
      GROUP BY i_manufact_id, d_qoy) tmp1
WHERE CASE WHEN avg_quarterly_sales > 0
           THEN ABS(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
           ELSE NULL END > 0.1
ORDER BY avg_quarterly_sales, sum_sales, i_manufact_id
LIMIT 100
