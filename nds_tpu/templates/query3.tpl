-- define [MANUFACT] = uniform_int(1, 1000)
-- define [MONTH] = uniform_int(11, 12)
SELECT dt.d_year, item.i_brand_id AS brand_id, item.i_brand AS brand,
       SUM(ss_ext_sales_price) AS sum_agg
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manufact_id = [MANUFACT]
  AND dt.d_moy = [MONTH]
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year, sum_agg DESC, brand_id
LIMIT 100
