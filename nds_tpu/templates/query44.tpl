-- define [STORE] = uniform_int(1, 4)
SELECT asceding.rnk, i1.i_product_name AS best_performing,
       i2.i_product_name AS worst_performing
FROM (SELECT *
      FROM (SELECT item_sk, RANK() OVER (ORDER BY rank_col ASC) AS rnk
            FROM (SELECT ss_item_sk AS item_sk,
                         AVG(ss_net_profit) AS rank_col
                  FROM store_sales ss1
                  WHERE ss_store_sk = [STORE]
                  GROUP BY ss_item_sk
                  HAVING AVG(ss_net_profit) > 0.9 *
                         (SELECT AVG(ss_net_profit) AS rank_col
                          FROM store_sales
                          WHERE ss_store_sk = [STORE]
                            AND ss_addr_sk IS NULL
                          GROUP BY ss_store_sk)) v1) v11
      WHERE rnk < 11) asceding,
     (SELECT *
      FROM (SELECT item_sk, RANK() OVER (ORDER BY rank_col DESC) AS rnk
            FROM (SELECT ss_item_sk AS item_sk,
                         AVG(ss_net_profit) AS rank_col
                  FROM store_sales ss1
                  WHERE ss_store_sk = [STORE]
                  GROUP BY ss_item_sk
                  HAVING AVG(ss_net_profit) > 0.9 *
                         (SELECT AVG(ss_net_profit) AS rank_col
                          FROM store_sales
                          WHERE ss_store_sk = [STORE]
                            AND ss_addr_sk IS NULL
                          GROUP BY ss_store_sk)) v2) v21
      WHERE rnk < 11) descending,
     item i1, item i2
WHERE asceding.rnk = descending.rnk
  AND i1.i_item_sk = asceding.item_sk
  AND i2.i_item_sk = descending.item_sk
ORDER BY asceding.rnk
LIMIT 100
