-- define [YEAR] = uniform_int(1998, 2002)
-- define [TIME] = uniform_int(0, 57597)
-- define [C1] = choice('UPS','FEDEX','AIRBORNE','USPS','DHL','TBS','ZHOU','MSC','LATVIAN','ALLIANCE')
-- define [C2] = choice('DIAMOND','RUPEKSA','ORIENTAL','BARIAN','BOXBUNDLES','GERMA','HARMSTORF','PRIVATECARRIER','ZOUROS','GREAT EASTERN')
SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
       w_country, ship_carriers, year_,
       SUM(jan_sales) AS jan_sales,
       SUM(feb_sales) AS feb_sales,
       SUM(mar_sales) AS mar_sales,
       SUM(apr_sales) AS apr_sales,
       SUM(may_sales) AS may_sales,
       SUM(jun_sales) AS jun_sales,
       SUM(jul_sales) AS jul_sales,
       SUM(aug_sales) AS aug_sales,
       SUM(sep_sales) AS sep_sales,
       SUM(oct_sales) AS oct_sales,
       SUM(nov_sales) AS nov_sales,
       SUM(dec_sales) AS dec_sales,
       SUM(jan_sales / w_warehouse_sq_ft) AS jan_sales_per_sq_foot,
       SUM(feb_sales / w_warehouse_sq_ft) AS feb_sales_per_sq_foot,
       SUM(mar_sales / w_warehouse_sq_ft) AS mar_sales_per_sq_foot,
       SUM(apr_sales / w_warehouse_sq_ft) AS apr_sales_per_sq_foot,
       SUM(may_sales / w_warehouse_sq_ft) AS may_sales_per_sq_foot,
       SUM(jun_sales / w_warehouse_sq_ft) AS jun_sales_per_sq_foot,
       SUM(jul_sales / w_warehouse_sq_ft) AS jul_sales_per_sq_foot,
       SUM(aug_sales / w_warehouse_sq_ft) AS aug_sales_per_sq_foot,
       SUM(sep_sales / w_warehouse_sq_ft) AS sep_sales_per_sq_foot,
       SUM(oct_sales / w_warehouse_sq_ft) AS oct_sales_per_sq_foot,
       SUM(nov_sales / w_warehouse_sq_ft) AS nov_sales_per_sq_foot,
       SUM(dec_sales / w_warehouse_sq_ft) AS dec_sales_per_sq_foot,
       SUM(jan_net) AS jan_net,
       SUM(feb_net) AS feb_net,
       SUM(mar_net) AS mar_net,
       SUM(apr_net) AS apr_net,
       SUM(may_net) AS may_net,
       SUM(jun_net) AS jun_net,
       SUM(jul_net) AS jul_net,
       SUM(aug_net) AS aug_net,
       SUM(sep_net) AS sep_net,
       SUM(oct_net) AS oct_net,
       SUM(nov_net) AS nov_net,
       SUM(dec_net) AS dec_net
FROM (SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
             w_country,
             CONCAT('[C1]', CONCAT(',', '[C2]')) AS ship_carriers,
             d_year AS year_,
             SUM(CASE WHEN d_moy = 1 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS jan_sales,
             SUM(CASE WHEN d_moy = 2 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS feb_sales,
             SUM(CASE WHEN d_moy = 3 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS mar_sales,
             SUM(CASE WHEN d_moy = 4 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS apr_sales,
             SUM(CASE WHEN d_moy = 5 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS may_sales,
             SUM(CASE WHEN d_moy = 6 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS jun_sales,
             SUM(CASE WHEN d_moy = 7 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS jul_sales,
             SUM(CASE WHEN d_moy = 8 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS aug_sales,
             SUM(CASE WHEN d_moy = 9 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS sep_sales,
             SUM(CASE WHEN d_moy = 10 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS oct_sales,
             SUM(CASE WHEN d_moy = 11 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS nov_sales,
             SUM(CASE WHEN d_moy = 12 THEN ws_ext_sales_price * ws_quantity ELSE 0 END) AS dec_sales,
             SUM(CASE WHEN d_moy = 1 THEN ws_net_paid * ws_quantity ELSE 0 END) AS jan_net,
             SUM(CASE WHEN d_moy = 2 THEN ws_net_paid * ws_quantity ELSE 0 END) AS feb_net,
             SUM(CASE WHEN d_moy = 3 THEN ws_net_paid * ws_quantity ELSE 0 END) AS mar_net,
             SUM(CASE WHEN d_moy = 4 THEN ws_net_paid * ws_quantity ELSE 0 END) AS apr_net,
             SUM(CASE WHEN d_moy = 5 THEN ws_net_paid * ws_quantity ELSE 0 END) AS may_net,
             SUM(CASE WHEN d_moy = 6 THEN ws_net_paid * ws_quantity ELSE 0 END) AS jun_net,
             SUM(CASE WHEN d_moy = 7 THEN ws_net_paid * ws_quantity ELSE 0 END) AS jul_net,
             SUM(CASE WHEN d_moy = 8 THEN ws_net_paid * ws_quantity ELSE 0 END) AS aug_net,
             SUM(CASE WHEN d_moy = 9 THEN ws_net_paid * ws_quantity ELSE 0 END) AS sep_net,
             SUM(CASE WHEN d_moy = 10 THEN ws_net_paid * ws_quantity ELSE 0 END) AS oct_net,
             SUM(CASE WHEN d_moy = 11 THEN ws_net_paid * ws_quantity ELSE 0 END) AS nov_net,
             SUM(CASE WHEN d_moy = 12 THEN ws_net_paid * ws_quantity ELSE 0 END) AS dec_net
      FROM web_sales, warehouse, date_dim, time_dim, ship_mode
      WHERE ws_warehouse_sk = w_warehouse_sk
        AND ws_sold_date_sk = d_date_sk
        AND ws_sold_time_sk = t_time_sk
        AND ws_ship_mode_sk = sm_ship_mode_sk
        AND d_year = [YEAR]
        AND t_time BETWEEN [TIME] AND [TIME] + 28800
        AND sm_carrier IN ('[C1]', '[C2]')
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
               w_state, w_country, d_year
      UNION ALL
      SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
             w_country,
             CONCAT('[C1]', CONCAT(',', '[C2]')) AS ship_carriers,
             d_year AS year_,
             SUM(CASE WHEN d_moy = 1 THEN cs_sales_price * cs_quantity ELSE 0 END) AS jan_sales,
             SUM(CASE WHEN d_moy = 2 THEN cs_sales_price * cs_quantity ELSE 0 END) AS feb_sales,
             SUM(CASE WHEN d_moy = 3 THEN cs_sales_price * cs_quantity ELSE 0 END) AS mar_sales,
             SUM(CASE WHEN d_moy = 4 THEN cs_sales_price * cs_quantity ELSE 0 END) AS apr_sales,
             SUM(CASE WHEN d_moy = 5 THEN cs_sales_price * cs_quantity ELSE 0 END) AS may_sales,
             SUM(CASE WHEN d_moy = 6 THEN cs_sales_price * cs_quantity ELSE 0 END) AS jun_sales,
             SUM(CASE WHEN d_moy = 7 THEN cs_sales_price * cs_quantity ELSE 0 END) AS jul_sales,
             SUM(CASE WHEN d_moy = 8 THEN cs_sales_price * cs_quantity ELSE 0 END) AS aug_sales,
             SUM(CASE WHEN d_moy = 9 THEN cs_sales_price * cs_quantity ELSE 0 END) AS sep_sales,
             SUM(CASE WHEN d_moy = 10 THEN cs_sales_price * cs_quantity ELSE 0 END) AS oct_sales,
             SUM(CASE WHEN d_moy = 11 THEN cs_sales_price * cs_quantity ELSE 0 END) AS nov_sales,
             SUM(CASE WHEN d_moy = 12 THEN cs_sales_price * cs_quantity ELSE 0 END) AS dec_sales,
             SUM(CASE WHEN d_moy = 1 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS jan_net,
             SUM(CASE WHEN d_moy = 2 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS feb_net,
             SUM(CASE WHEN d_moy = 3 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS mar_net,
             SUM(CASE WHEN d_moy = 4 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS apr_net,
             SUM(CASE WHEN d_moy = 5 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS may_net,
             SUM(CASE WHEN d_moy = 6 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS jun_net,
             SUM(CASE WHEN d_moy = 7 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS jul_net,
             SUM(CASE WHEN d_moy = 8 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS aug_net,
             SUM(CASE WHEN d_moy = 9 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS sep_net,
             SUM(CASE WHEN d_moy = 10 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS oct_net,
             SUM(CASE WHEN d_moy = 11 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS nov_net,
             SUM(CASE WHEN d_moy = 12 THEN cs_net_paid_inc_tax * cs_quantity ELSE 0 END) AS dec_net
      FROM catalog_sales, warehouse, date_dim, time_dim, ship_mode
      WHERE cs_warehouse_sk = w_warehouse_sk
        AND cs_sold_date_sk = d_date_sk
        AND cs_sold_time_sk = t_time_sk
        AND cs_ship_mode_sk = sm_ship_mode_sk
        AND d_year = [YEAR]
        AND t_time BETWEEN [TIME] AND [TIME] + 28800
        AND sm_carrier IN ('[C1]', '[C2]')
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
               w_state, w_country, d_year) x
GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
         w_country, ship_carriers, year_
ORDER BY w_warehouse_name
LIMIT 100
