-- define [CATEGORY] = choice_n(3, 'Books', 'Children', 'Electronics', 'Home', 'Jewelry', 'Men', 'Music', 'Shoes', 'Sports', 'Women')
-- define [YEAR] = uniform_int(1998, 2002)
-- define [MONTH] = uniform_int(1, 7)
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       SUM(ws_ext_sales_price) AS itemrevenue,
       SUM(ws_ext_sales_price) * 100 /
         SUM(SUM(ws_ext_sales_price)) OVER (PARTITION BY i_class)
         AS revenueratio
FROM web_sales, item, date_dim
WHERE ws_item_sk = i_item_sk
  AND i_category IN ([CATEGORY])
  AND ws_sold_date_sk = d_date_sk
  AND d_date BETWEEN CAST('[YEAR]-0[MONTH]-01' AS DATE)
                 AND CAST('[YEAR]-0[MONTH]-01' AS DATE) + INTERVAL 30 DAYS
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
