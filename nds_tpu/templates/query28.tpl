-- define [LP] = uniform_int(0, 190)
-- define [CP] = uniform_int(0, 18000)
-- define [WC] = uniform_int(0, 80)
SELECT *
FROM (SELECT AVG(ss_list_price) AS b1_lp,
             COUNT(ss_list_price) AS b1_cnt,
             COUNT(DISTINCT ss_list_price) AS b1_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 0 AND 5
        AND (ss_list_price BETWEEN [LP] AND [LP] + 10
             OR ss_coupon_amt BETWEEN [CP] AND [CP] + 1000
             OR ss_wholesale_cost BETWEEN [WC] AND [WC] + 20)) b1,
     (SELECT AVG(ss_list_price) AS b2_lp,
             COUNT(ss_list_price) AS b2_cnt,
             COUNT(DISTINCT ss_list_price) AS b2_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 6 AND 10
        AND (ss_list_price BETWEEN [LP] + 10 AND [LP] + 20
             OR ss_coupon_amt BETWEEN [CP] + 1000 AND [CP] + 2000
             OR ss_wholesale_cost BETWEEN [WC] + 10 AND [WC] + 30)) b2,
     (SELECT AVG(ss_list_price) AS b3_lp,
             COUNT(ss_list_price) AS b3_cnt,
             COUNT(DISTINCT ss_list_price) AS b3_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 11 AND 15
        AND (ss_list_price BETWEEN [LP] + 20 AND [LP] + 30
             OR ss_coupon_amt BETWEEN [CP] + 2000 AND [CP] + 3000
             OR ss_wholesale_cost BETWEEN [WC] + 20 AND [WC] + 40)) b3,
     (SELECT AVG(ss_list_price) AS b4_lp,
             COUNT(ss_list_price) AS b4_cnt,
             COUNT(DISTINCT ss_list_price) AS b4_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 16 AND 20
        AND (ss_list_price BETWEEN [LP] + 30 AND [LP] + 40
             OR ss_coupon_amt BETWEEN [CP] + 3000 AND [CP] + 4000
             OR ss_wholesale_cost BETWEEN [WC] + 30 AND [WC] + 50)) b4,
     (SELECT AVG(ss_list_price) AS b5_lp,
             COUNT(ss_list_price) AS b5_cnt,
             COUNT(DISTINCT ss_list_price) AS b5_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 21 AND 25
        AND (ss_list_price BETWEEN [LP] + 40 AND [LP] + 50
             OR ss_coupon_amt BETWEEN [CP] + 4000 AND [CP] + 5000
             OR ss_wholesale_cost BETWEEN [WC] + 40 AND [WC] + 60)) b5,
     (SELECT AVG(ss_list_price) AS b6_lp,
             COUNT(ss_list_price) AS b6_cnt,
             COUNT(DISTINCT ss_list_price) AS b6_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 26 AND 30
        AND (ss_list_price BETWEEN [LP] + 50 AND [LP] + 60
             OR ss_coupon_amt BETWEEN [CP] + 5000 AND [CP] + 6000
             OR ss_wholesale_cost BETWEEN [WC] + 50 AND [WC] + 70)) b6
LIMIT 100
