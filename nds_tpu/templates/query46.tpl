-- define [YEAR] = uniform_int(1998, 2000)
-- define [DEP] = uniform_int(0, 6)
-- define [VEH] = uniform_int(-1, 4)
-- define [CITIES] = choice_n(5, 'Fairview','Midway','Oak Grove','Five Points','Pleasant Hill','Centerville','Riverside','Salem','Liberty','Greenville')
SELECT c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city AS bought_city,
             SUM(ss_coupon_amt) AS amt, SUM(ss_net_profit) AS profit
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        AND store_sales.ss_addr_sk = customer_address.ca_address_sk
        AND (household_demographics.hd_dep_count = [DEP]
             OR household_demographics.hd_vehicle_count = [VEH])
        AND date_dim.d_dow IN (6, 0)
        AND date_dim.d_year IN ([YEAR], [YEAR] + 1, [YEAR] + 2)
        AND store.s_city IN ([CITIES])
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
LIMIT 100
