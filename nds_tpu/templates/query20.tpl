-- define [SDATE] = rand_date(1999, 2002)
-- define [CATS] = choice_n(3, 'Books','Children','Electronics','Home','Jewelry','Men','Music','Shoes','Sports','Women')
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       SUM(cs_ext_sales_price) AS itemrevenue,
       SUM(cs_ext_sales_price) * 100 /
           SUM(SUM(cs_ext_sales_price)) OVER (PARTITION BY i_class)
           AS revenueratio
FROM catalog_sales, item, date_dim
WHERE cs_item_sk = i_item_sk
  AND i_category IN ([CATS])
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                 AND (CAST('[SDATE]' AS DATE) + INTERVAL 30 DAYS)
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
