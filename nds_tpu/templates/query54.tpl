-- define [YEAR] = uniform_int(1998, 2002)
-- define [MONTH] = uniform_int(1, 7)
-- define [CATEGORY] = choice('Books','Children','Electronics','Home','Jewelry','Men','Music','Shoes','Sports','Women')
-- define [CLASS] = choice('accent','accessories','archery','arts','athletic','audio','automotive','baseball')
WITH my_customers AS (
  SELECT DISTINCT c_customer_sk, c_current_addr_sk
  FROM (SELECT cs_sold_date_sk AS sold_date_sk,
               cs_bill_customer_sk AS customer_sk,
               cs_item_sk AS item_sk
        FROM catalog_sales
        UNION ALL
        SELECT ws_sold_date_sk AS sold_date_sk,
               ws_bill_customer_sk AS customer_sk,
               ws_item_sk AS item_sk
        FROM web_sales) cs_or_ws_sales, item, date_dim, customer
  WHERE sold_date_sk = d_date_sk
    AND item_sk = i_item_sk
    AND i_category = '[CATEGORY]'
    AND i_class = '[CLASS]'
    AND c_customer_sk = cs_or_ws_sales.customer_sk
    AND d_moy = [MONTH]
    AND d_year = [YEAR]
),
my_revenue AS (
  SELECT c_customer_sk, SUM(ss_ext_sales_price) AS revenue
  FROM my_customers, store_sales, customer_address, store, date_dim
  WHERE c_current_addr_sk = ca_address_sk
    AND ca_county = s_county
    AND ca_state = s_state
    AND ss_customer_sk = c_customer_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN (SELECT DISTINCT d_month_seq + 1
                             FROM date_dim
                             WHERE d_year = [YEAR] AND d_moy = [MONTH])
                        AND (SELECT DISTINCT d_month_seq + 3
                             FROM date_dim
                             WHERE d_year = [YEAR] AND d_moy = [MONTH])
  GROUP BY c_customer_sk
),
segments AS (
  SELECT CAST((revenue / 50) AS INT) AS segment FROM my_revenue
)
SELECT segment, COUNT(*) AS num_customers, segment * 50 AS segment_base
FROM segments
GROUP BY segment
ORDER BY segment, num_customers
LIMIT 100
