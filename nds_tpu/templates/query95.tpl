-- define [DATE] = rand_date(1999, 2002)
-- define [STATE] = choice('GA','ID','IL','IN','IA','KS','KY','LA','MD','MA')
-- define [COMPANY] = choice('ought','able','pri','ese','anti','cally','ation','eing','n st','bar')
WITH ws_wh AS (
  SELECT ws1.ws_order_number, ws1.ws_warehouse_sk AS wh1,
         ws2.ws_warehouse_sk AS wh2
  FROM web_sales ws1, web_sales ws2
  WHERE ws1.ws_order_number = ws2.ws_order_number
    AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk
)
SELECT COUNT(DISTINCT ws_order_number) AS order_count,
       SUM(ws_ext_ship_cost) AS total_shipping_cost,
       SUM(ws_net_profit) AS total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN CAST('[DATE]' AS DATE)
                 AND (CAST('[DATE]' AS DATE) + INTERVAL 60 DAYS)
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk
  AND ca_state = '[STATE]'
  AND ws1.ws_web_site_sk = web_site_sk
  AND web_company_name = '[COMPANY]'
  AND ws1.ws_order_number IN (SELECT ws_order_number FROM ws_wh)
  AND ws1.ws_order_number IN (SELECT wr_order_number
                              FROM web_returns, ws_wh
                              WHERE wr_order_number = ws_wh.ws_order_number)
ORDER BY order_count
LIMIT 100
