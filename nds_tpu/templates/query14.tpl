-- define [YEAR] = uniform_int(1998, 2000)
-- define [DAY] = uniform_int(1, 28)
WITH cross_items AS (
  SELECT i_item_sk AS ss_item_sk
  FROM item,
       (SELECT iss.i_brand_id AS brand_id, iss.i_class_id AS class_id,
               iss.i_category_id AS category_id
        FROM store_sales, item iss, date_dim d1
        WHERE ss_item_sk = iss.i_item_sk
          AND ss_sold_date_sk = d1.d_date_sk
          AND d1.d_year BETWEEN [YEAR] AND [YEAR] + 2
        INTERSECT
        SELECT ics.i_brand_id, ics.i_class_id, ics.i_category_id
        FROM catalog_sales, item ics, date_dim d2
        WHERE cs_item_sk = ics.i_item_sk
          AND cs_sold_date_sk = d2.d_date_sk
          AND d2.d_year BETWEEN [YEAR] AND [YEAR] + 2
        INTERSECT
        SELECT iws.i_brand_id, iws.i_class_id, iws.i_category_id
        FROM web_sales, item iws, date_dim d3
        WHERE ws_item_sk = iws.i_item_sk
          AND ws_sold_date_sk = d3.d_date_sk
          AND d3.d_year BETWEEN [YEAR] AND [YEAR] + 2) x
  WHERE i_brand_id = brand_id
    AND i_class_id = class_id
    AND i_category_id = category_id
),
avg_sales AS (
  SELECT AVG(quantity * list_price) AS average_sales
  FROM (SELECT ss_quantity AS quantity, ss_list_price AS list_price
        FROM store_sales, date_dim
        WHERE ss_sold_date_sk = d_date_sk
          AND d_year BETWEEN [YEAR] AND [YEAR] + 2
        UNION ALL
        SELECT cs_quantity AS quantity, cs_list_price AS list_price
        FROM catalog_sales, date_dim
        WHERE cs_sold_date_sk = d_date_sk
          AND d_year BETWEEN [YEAR] AND [YEAR] + 2
        UNION ALL
        SELECT ws_quantity AS quantity, ws_list_price AS list_price
        FROM web_sales, date_dim
        WHERE ws_sold_date_sk = d_date_sk
          AND d_year BETWEEN [YEAR] AND [YEAR] + 2) x
)
SELECT channel, i_brand_id, i_class_id, i_category_id,
       SUM(sales) AS sales_sum, SUM(number_sales) AS number_sales_sum
FROM (SELECT 'store' AS channel, i_brand_id, i_class_id, i_category_id,
             SUM(ss_quantity * ss_list_price) AS sales,
             COUNT(*) AS number_sales
      FROM store_sales, item, date_dim
      WHERE ss_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND ss_item_sk = i_item_sk
        AND ss_sold_date_sk = d_date_sk
        AND d_year = [YEAR] + 2
        AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING SUM(ss_quantity * ss_list_price) >
             (SELECT average_sales FROM avg_sales)
      UNION ALL
      SELECT 'catalog' AS channel, i_brand_id, i_class_id, i_category_id,
             SUM(cs_quantity * cs_list_price) AS sales,
             COUNT(*) AS number_sales
      FROM catalog_sales, item, date_dim
      WHERE cs_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND cs_item_sk = i_item_sk
        AND cs_sold_date_sk = d_date_sk
        AND d_year = [YEAR] + 2
        AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING SUM(cs_quantity * cs_list_price) >
             (SELECT average_sales FROM avg_sales)
      UNION ALL
      SELECT 'web' AS channel, i_brand_id, i_class_id, i_category_id,
             SUM(ws_quantity * ws_list_price) AS sales,
             COUNT(*) AS number_sales
      FROM web_sales, item, date_dim
      WHERE ws_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND ws_item_sk = i_item_sk
        AND ws_sold_date_sk = d_date_sk
        AND d_year = [YEAR] + 2
        AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING SUM(ws_quantity * ws_list_price) >
             (SELECT average_sales FROM avg_sales)) y
GROUP BY ROLLUP (channel, i_brand_id, i_class_id, i_category_id)
ORDER BY channel, i_brand_id, i_class_id, i_category_id
LIMIT 100;
WITH cross_items AS (
  SELECT i_item_sk AS ss_item_sk
  FROM item,
       (SELECT iss.i_brand_id AS brand_id, iss.i_class_id AS class_id,
               iss.i_category_id AS category_id
        FROM store_sales, item iss, date_dim d1
        WHERE ss_item_sk = iss.i_item_sk
          AND ss_sold_date_sk = d1.d_date_sk
          AND d1.d_year BETWEEN [YEAR] AND [YEAR] + 2
        INTERSECT
        SELECT ics.i_brand_id, ics.i_class_id, ics.i_category_id
        FROM catalog_sales, item ics, date_dim d2
        WHERE cs_item_sk = ics.i_item_sk
          AND cs_sold_date_sk = d2.d_date_sk
          AND d2.d_year BETWEEN [YEAR] AND [YEAR] + 2
        INTERSECT
        SELECT iws.i_brand_id, iws.i_class_id, iws.i_category_id
        FROM web_sales, item iws, date_dim d3
        WHERE ws_item_sk = iws.i_item_sk
          AND ws_sold_date_sk = d3.d_date_sk
          AND d3.d_year BETWEEN [YEAR] AND [YEAR] + 2) x
  WHERE i_brand_id = brand_id
    AND i_class_id = class_id
    AND i_category_id = category_id
),
avg_sales AS (
  SELECT AVG(quantity * list_price) AS average_sales
  FROM (SELECT ss_quantity AS quantity, ss_list_price AS list_price
        FROM store_sales, date_dim
        WHERE ss_sold_date_sk = d_date_sk
          AND d_year BETWEEN [YEAR] AND [YEAR] + 2
        UNION ALL
        SELECT cs_quantity AS quantity, cs_list_price AS list_price
        FROM catalog_sales, date_dim
        WHERE cs_sold_date_sk = d_date_sk
          AND d_year BETWEEN [YEAR] AND [YEAR] + 2
        UNION ALL
        SELECT ws_quantity AS quantity, ws_list_price AS list_price
        FROM web_sales, date_dim
        WHERE ws_sold_date_sk = d_date_sk
          AND d_year BETWEEN [YEAR] AND [YEAR] + 2) x
)
SELECT this_year.channel AS ty_channel,
       this_year.i_brand_id AS ty_brand,
       this_year.i_class_id AS ty_class,
       this_year.i_category_id AS ty_category,
       this_year.sales AS ty_sales,
       this_year.number_sales AS ty_number_sales,
       last_year.channel AS ly_channel,
       last_year.i_brand_id AS ly_brand,
       last_year.i_class_id AS ly_class,
       last_year.i_category_id AS ly_category,
       last_year.sales AS ly_sales,
       last_year.number_sales AS ly_number_sales
FROM (SELECT 'store' AS channel, i_brand_id, i_class_id, i_category_id,
             SUM(ss_quantity * ss_list_price) AS sales,
             COUNT(*) AS number_sales
      FROM store_sales, item, date_dim
      WHERE ss_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND ss_item_sk = i_item_sk
        AND ss_sold_date_sk = d_date_sk
        AND d_week_seq = (SELECT d_week_seq
                          FROM date_dim
                          WHERE d_year = [YEAR] + 1
                            AND d_moy = 12
                            AND d_dom = [DAY])
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING SUM(ss_quantity * ss_list_price) >
             (SELECT average_sales FROM avg_sales)) this_year,
     (SELECT 'store' AS channel, i_brand_id, i_class_id, i_category_id,
             SUM(ss_quantity * ss_list_price) AS sales,
             COUNT(*) AS number_sales
      FROM store_sales, item, date_dim
      WHERE ss_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND ss_item_sk = i_item_sk
        AND ss_sold_date_sk = d_date_sk
        AND d_week_seq = (SELECT d_week_seq
                          FROM date_dim
                          WHERE d_year = [YEAR]
                            AND d_moy = 12
                            AND d_dom = [DAY])
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING SUM(ss_quantity * ss_list_price) >
             (SELECT average_sales FROM avg_sales)) last_year
WHERE this_year.i_brand_id = last_year.i_brand_id
  AND this_year.i_class_id = last_year.i_class_id
  AND this_year.i_category_id = last_year.i_category_id
ORDER BY this_year.channel, this_year.i_brand_id, this_year.i_class_id,
         this_year.i_category_id
LIMIT 100
