-- define [YEAR] = uniform_int(1999, 2001)
WITH year_total AS (
  SELECT c_customer_id AS customer_id,
         c_first_name AS customer_first_name,
         c_last_name AS customer_last_name,
         c_preferred_cust_flag AS customer_preferred_cust_flag,
         c_birth_country AS customer_birth_country,
         c_login AS customer_login,
         c_email_address AS customer_email_address,
         d_year AS dyear,
         SUM(((ss_ext_list_price - ss_ext_wholesale_cost
               - ss_ext_discount_amt) + ss_ext_sales_price) / 2)
             AS year_total,
         's' AS sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
           c_birth_country, c_login, c_email_address, d_year
  UNION ALL
  SELECT c_customer_id AS customer_id,
         c_first_name AS customer_first_name,
         c_last_name AS customer_last_name,
         c_preferred_cust_flag AS customer_preferred_cust_flag,
         c_birth_country AS customer_birth_country,
         c_login AS customer_login,
         c_email_address AS customer_email_address,
         d_year AS dyear,
         SUM((((cs_ext_list_price - cs_ext_wholesale_cost
                - cs_ext_discount_amt) + cs_ext_sales_price) / 2))
             AS year_total,
         'c' AS sale_type
  FROM customer, catalog_sales, date_dim
  WHERE c_customer_sk = cs_bill_customer_sk AND cs_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
           c_birth_country, c_login, c_email_address, d_year
  UNION ALL
  SELECT c_customer_id AS customer_id,
         c_first_name AS customer_first_name,
         c_last_name AS customer_last_name,
         c_preferred_cust_flag AS customer_preferred_cust_flag,
         c_birth_country AS customer_birth_country,
         c_login AS customer_login,
         c_email_address AS customer_email_address,
         d_year AS dyear,
         SUM((((ws_ext_list_price - ws_ext_wholesale_cost
                - ws_ext_discount_amt) + ws_ext_sales_price) / 2))
             AS year_total,
         'w' AS sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
           c_birth_country, c_login, c_email_address, d_year
)
SELECT t_s_secyear.customer_id,
       t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name,
       t_s_secyear.customer_preferred_cust_flag
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_c_firstyear, year_total t_c_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_c_secyear.customer_id
  AND t_s_firstyear.customer_id = t_c_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.sale_type = 's'
  AND t_c_firstyear.sale_type = 'c'
  AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's'
  AND t_c_secyear.sale_type = 'c'
  AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = [YEAR]
  AND t_s_secyear.dyear = [YEAR] + 1
  AND t_c_firstyear.dyear = [YEAR]
  AND t_c_secyear.dyear = [YEAR] + 1
  AND t_w_firstyear.dyear = [YEAR]
  AND t_w_secyear.dyear = [YEAR] + 1
  AND t_s_firstyear.year_total > 0
  AND t_c_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN t_c_secyear.year_total / t_c_firstyear.year_total
           ELSE NULL END
      > CASE WHEN t_s_firstyear.year_total > 0
             THEN t_s_secyear.year_total / t_s_firstyear.year_total
             ELSE NULL END
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN t_c_secyear.year_total / t_c_firstyear.year_total
           ELSE NULL END
      > CASE WHEN t_w_firstyear.year_total > 0
             THEN t_w_secyear.year_total / t_w_firstyear.year_total
             ELSE NULL END
ORDER BY t_s_secyear.customer_id,
         t_s_secyear.customer_first_name,
         t_s_secyear.customer_last_name,
         t_s_secyear.customer_preferred_cust_flag
LIMIT 100
