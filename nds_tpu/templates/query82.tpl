-- define [PRICE] = uniform_int(10, 60)
-- define [SDATE] = rand_date(1998, 2002)
-- define [MANUFACTS] = choice_n(4, 129, 270, 821, 423, 129, 271, 917, 318, 561, 95, 742, 134, 606, 882, 283, 553, 651, 774, 818, 995)
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN [PRICE] AND [PRICE] + 30
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                 AND (CAST('[SDATE]' AS DATE) + INTERVAL 60 DAYS)
  AND i_manufact_id IN ([MANUFACTS])
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id
LIMIT 100
