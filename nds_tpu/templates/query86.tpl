-- define [DMS] = uniform_int(1176, 1224)
SELECT SUM(ws_net_paid) AS total_sum, i_category, i_class,
       GROUPING(i_category) + GROUPING(i_class) AS lochierarchy,
       RANK() OVER (PARTITION BY GROUPING(i_category) + GROUPING(i_class),
                                 CASE WHEN GROUPING(i_class) = 0
                                      THEN i_category END
                    ORDER BY SUM(ws_net_paid) DESC) AS rank_within_parent
FROM web_sales, date_dim d1, item
WHERE d1.d_month_seq BETWEEN [DMS] AND [DMS] + 11
  AND d1.d_date_sk = ws_sold_date_sk
  AND i_item_sk = ws_item_sk
GROUP BY ROLLUP (i_category, i_class)
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN i_category END,
         rank_within_parent
LIMIT 100
