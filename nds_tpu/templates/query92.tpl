-- define [IMID] = uniform_int(1, 1000)
-- define [SDATE] = rand_date(1998, 2002)
SELECT SUM(ws_ext_discount_amt) AS excess_discount_amount
FROM web_sales, item, date_dim
WHERE i_manufact_id = [IMID]
  AND i_item_sk = ws_item_sk
  AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                 AND (CAST('[SDATE]' AS DATE) + INTERVAL 90 DAYS)
  AND d_date_sk = ws_sold_date_sk
  AND ws_ext_discount_amt >
      (SELECT 1.3 * AVG(ws_ext_discount_amt)
       FROM web_sales, date_dim
       WHERE ws_item_sk = i_item_sk
         AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                        AND (CAST('[SDATE]' AS DATE) + INTERVAL 90 DAYS)
         AND d_date_sk = ws_sold_date_sk)
ORDER BY SUM(ws_ext_discount_amt)
LIMIT 100
