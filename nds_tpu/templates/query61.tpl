-- define [YEAR] = uniform_int(1998, 2002)
-- define [MONTH] = uniform_int(11, 12)
-- define [CATEGORY] = choice('Books','Children','Electronics','Home','Jewelry','Men','Music','Shoes','Sports','Women')
-- define [GMT] = choice('-5', '-6', '-7')
SELECT promotions, total,
       CAST(promotions AS DOUBLE) / CAST(total AS DOUBLE) * 100 AS ratio
FROM (SELECT SUM(ss_ext_sales_price) AS promotions
      FROM store_sales, store, promotion, date_dim, customer,
           customer_address, item
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_promo_sk = p_promo_sk
        AND ss_customer_sk = c_customer_sk
        AND ca_address_sk = c_current_addr_sk
        AND ss_item_sk = i_item_sk
        AND ca_gmt_offset = [GMT]
        AND i_category = '[CATEGORY]'
        AND (p_channel_dmail = 'Y' OR p_channel_email = 'Y'
             OR p_channel_tv = 'Y')
        AND s_gmt_offset = [GMT]
        AND d_year = [YEAR]
        AND d_moy = [MONTH]) promotional_sales,
     (SELECT SUM(ss_ext_sales_price) AS total
      FROM store_sales, store, date_dim, customer, customer_address, item
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_customer_sk = c_customer_sk
        AND ca_address_sk = c_current_addr_sk
        AND ss_item_sk = i_item_sk
        AND ca_gmt_offset = [GMT]
        AND i_category = '[CATEGORY]'
        AND s_gmt_offset = [GMT]
        AND d_year = [YEAR]
        AND d_moy = [MONTH]) all_sales
ORDER BY promotions, total
LIMIT 100
