-- define [YEAR] = uniform_int(1999, 2002)
SELECT ca_state, cd_gender, cd_marital_status, cd_dep_count,
       COUNT(*) AS cnt1,
       MIN(cd_dep_count) AS min_dep, MAX(cd_dep_count) AS max_dep,
       AVG(cd_dep_count) AS avg_dep,
       cd_dep_employed_count, COUNT(*) AS cnt2,
       MIN(cd_dep_employed_count) AS min_emp,
       MAX(cd_dep_employed_count) AS max_emp,
       AVG(cd_dep_employed_count) AS avg_emp,
       cd_dep_college_count, COUNT(*) AS cnt3,
       MIN(cd_dep_college_count) AS min_col,
       MAX(cd_dep_college_count) AS max_col,
       AVG(cd_dep_college_count) AS avg_col
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT *
              FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = [YEAR]
                AND d_qoy < 4)
  AND (EXISTS (SELECT *
               FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk
                 AND d_year = [YEAR]
                 AND d_qoy < 4)
       OR EXISTS (SELECT *
                  FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = [YEAR]
                    AND d_qoy < 4))
GROUP BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
ORDER BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
LIMIT 100
