-- define [YEAR] = uniform_int(1998, 2002)
-- define [MONTH] = uniform_int(8, 10)
SELECT s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state, s_zip,
       SUM(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk <= 30)
                THEN 1 ELSE 0 END) AS days_30,
       SUM(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 30)
                 AND (sr_returned_date_sk - ss_sold_date_sk <= 60)
                THEN 1 ELSE 0 END) AS days_31_60,
       SUM(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 60)
                 AND (sr_returned_date_sk - ss_sold_date_sk <= 90)
                THEN 1 ELSE 0 END) AS days_61_90,
       SUM(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 90)
                 AND (sr_returned_date_sk - ss_sold_date_sk <= 120)
                THEN 1 ELSE 0 END) AS days_91_120,
       SUM(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 120)
                THEN 1 ELSE 0 END) AS days_over_120
FROM store_sales, store_returns, store, date_dim d1, date_dim d2
WHERE d2.d_year = [YEAR]
  AND d2.d_moy = [MONTH]
  AND ss_ticket_number = sr_ticket_number
  AND ss_item_sk = sr_item_sk
  AND ss_sold_date_sk = d1.d_date_sk
  AND sr_returned_date_sk = d2.d_date_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_store_sk = s_store_sk
GROUP BY s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
ORDER BY s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
LIMIT 100
