-- define [YEAR] = uniform_int(1998, 2002)
WITH ws AS (
  SELECT d_year AS ws_sold_year, ws_item_sk,
         ws_bill_customer_sk AS ws_customer_sk,
         SUM(ws_quantity) AS ws_qty,
         SUM(ws_wholesale_cost) AS ws_wc,
         SUM(ws_sales_price) AS ws_sp
  FROM web_sales
       LEFT JOIN web_returns ON (wr_order_number = ws_order_number
                                 AND ws_item_sk = wr_item_sk)
       JOIN date_dim ON (ws_sold_date_sk = d_date_sk)
  WHERE wr_order_number IS NULL
  GROUP BY d_year, ws_item_sk, ws_bill_customer_sk
),
cs AS (
  SELECT d_year AS cs_sold_year, cs_item_sk,
         cs_bill_customer_sk AS cs_customer_sk,
         SUM(cs_quantity) AS cs_qty,
         SUM(cs_wholesale_cost) AS cs_wc,
         SUM(cs_sales_price) AS cs_sp
  FROM catalog_sales
       LEFT JOIN catalog_returns ON (cr_order_number = cs_order_number
                                     AND cs_item_sk = cr_item_sk)
       JOIN date_dim ON (cs_sold_date_sk = d_date_sk)
  WHERE cr_order_number IS NULL
  GROUP BY d_year, cs_item_sk, cs_bill_customer_sk
),
ss AS (
  SELECT d_year AS ss_sold_year, ss_item_sk,
         ss_customer_sk,
         SUM(ss_quantity) AS ss_qty,
         SUM(ss_wholesale_cost) AS ss_wc,
         SUM(ss_sales_price) AS ss_sp
  FROM store_sales
       LEFT JOIN store_returns ON (sr_ticket_number = ss_ticket_number
                                   AND ss_item_sk = sr_item_sk)
       JOIN date_dim ON (ss_sold_date_sk = d_date_sk)
  WHERE sr_ticket_number IS NULL
  GROUP BY d_year, ss_item_sk, ss_customer_sk
)
SELECT ss_sold_year, ss_item_sk, ss_customer_sk,
       ROUND(ss_qty / (COALESCE(ws_qty, 0) + COALESCE(cs_qty, 0)), 2)
           AS ratio,
       ss_qty AS store_qty, ss_wc AS store_wholesale_cost,
       ss_sp AS store_sales_price,
       COALESCE(ws_qty, 0) + COALESCE(cs_qty, 0) AS other_chan_qty,
       COALESCE(ws_wc, 0) + COALESCE(cs_wc, 0)
           AS other_chan_wholesale_cost,
       COALESCE(ws_sp, 0) + COALESCE(cs_sp, 0) AS other_chan_sales_price
FROM ss
     LEFT JOIN ws ON (ws_sold_year = ss_sold_year
                      AND ws_item_sk = ss_item_sk
                      AND ws_customer_sk = ss_customer_sk)
     LEFT JOIN cs ON (cs_sold_year = ss_sold_year
                      AND cs_item_sk = ss_item_sk
                      AND cs_customer_sk = ss_customer_sk)
WHERE (COALESCE(ws_qty, 0) > 0 OR COALESCE(cs_qty, 0) > 0)
  AND ss_sold_year = [YEAR]
ORDER BY ss_sold_year, ss_item_sk, ss_customer_sk, ss_qty DESC,
         ss_wc DESC, ss_sp DESC, other_chan_qty,
         other_chan_wholesale_cost, other_chan_sales_price, ratio
LIMIT 100
