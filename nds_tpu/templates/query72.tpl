-- define [YEAR] = uniform_int(1998, 2002)
-- define [BP] = choice('>10000', '5001-10000', '1001-5000', '501-1000', '0-500', 'Unknown')
-- define [MS] = choice('S','M','D','W','U')
SELECT i_item_desc, w_warehouse_name, d1.d_week_seq,
       SUM(CASE WHEN p_promo_sk IS NULL THEN 1 ELSE 0 END) AS no_promo,
       SUM(CASE WHEN p_promo_sk IS NOT NULL THEN 1 ELSE 0 END) AS promo,
       COUNT(*) AS total_cnt
FROM catalog_sales
     JOIN inventory ON (cs_item_sk = inv_item_sk)
     JOIN warehouse ON (w_warehouse_sk = inv_warehouse_sk)
     JOIN item ON (i_item_sk = cs_item_sk)
     JOIN customer_demographics ON (cs_bill_cdemo_sk = cd_demo_sk)
     JOIN household_demographics ON (cs_bill_hdemo_sk = hd_demo_sk)
     JOIN date_dim d1 ON (cs_sold_date_sk = d1.d_date_sk)
     JOIN date_dim d2 ON (inv_date_sk = d2.d_date_sk)
     JOIN date_dim d3 ON (cs_ship_date_sk = d3.d_date_sk)
     LEFT OUTER JOIN promotion ON (cs_promo_sk = p_promo_sk)
     LEFT OUTER JOIN catalog_returns ON (cr_item_sk = cs_item_sk
                                         AND cr_order_number = cs_order_number)
WHERE d1.d_week_seq = d2.d_week_seq
  AND inv_quantity_on_hand < cs_quantity
  AND d3.d_date > d1.d_date + INTERVAL 5 DAYS
  AND hd_buy_potential = '[BP]'
  AND d1.d_year = [YEAR]
  AND cd_marital_status = '[MS]'
GROUP BY i_item_desc, w_warehouse_name, d1.d_week_seq
ORDER BY total_cnt DESC, i_item_desc, w_warehouse_name, d1.d_week_seq
LIMIT 100
