-- define [DMS] = uniform_int(1176, 1224)
SELECT SUBSTR(w_warehouse_name, 1, 20) AS warehouse_name, sm_type, web_name,
       SUM(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk <= 30)
                THEN 1 ELSE 0 END) AS days_30,
       SUM(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 30)
                 AND (ws_ship_date_sk - ws_sold_date_sk <= 60)
                THEN 1 ELSE 0 END) AS days_31_60,
       SUM(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 60)
                 AND (ws_ship_date_sk - ws_sold_date_sk <= 90)
                THEN 1 ELSE 0 END) AS days_61_90,
       SUM(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 90)
                 AND (ws_ship_date_sk - ws_sold_date_sk <= 120)
                THEN 1 ELSE 0 END) AS days_91_120,
       SUM(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 120)
                THEN 1 ELSE 0 END) AS days_over_120
FROM web_sales, warehouse, ship_mode, web_site, date_dim
WHERE d_month_seq BETWEEN [DMS] AND [DMS] + 11
  AND ws_ship_date_sk = d_date_sk
  AND ws_warehouse_sk = w_warehouse_sk
  AND ws_ship_mode_sk = sm_ship_mode_sk
  AND ws_web_site_sk = web_site_sk
GROUP BY SUBSTR(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY SUBSTR(w_warehouse_name, 1, 20), sm_type, web_name
LIMIT 100
