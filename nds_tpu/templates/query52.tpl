-- define [YEAR] = uniform_int(1998, 2002)
-- define [MONTH] = uniform_int(11, 12)
SELECT dt.d_year, item.i_brand_id AS brand_id, item.i_brand AS brand,
       SUM(ss_ext_sales_price) AS ext_price
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manager_id = 1
  AND dt.d_moy = [MONTH]
  AND dt.d_year = [YEAR]
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year, ext_price DESC, brand_id
LIMIT 100
