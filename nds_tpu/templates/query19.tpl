-- define [YEAR] = uniform_int(1998, 2002)
-- define [MONTH] = uniform_int(11, 12)
-- define [MANAGER] = uniform_int(1, 100)
SELECT i_brand_id AS brand_id, i_brand AS brand, i_manufact_id, i_manufact,
       SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = [MANAGER]
  AND d_moy = [MONTH]
  AND d_year = [YEAR]
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND SUBSTR(ca_zip, 1, 5) <> SUBSTR(s_zip, 1, 5)
  AND ss_store_sk = s_store_sk
GROUP BY i_brand, i_brand_id, i_manufact_id, i_manufact
ORDER BY ext_price DESC, i_brand, i_brand_id, i_manufact_id, i_manufact
LIMIT 100
