-- define [YEAR] = uniform_int(1998, 2002)
-- define [MONTH] = uniform_int(11, 12)
-- define [MANAGER] = uniform_int(1, 100)
SELECT i_brand_id AS brand_id, i_brand AS brand,
       SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = [MANAGER]
  AND d_moy = [MONTH]
  AND d_year = [YEAR]
GROUP BY i_brand, i_brand_id
ORDER BY ext_price DESC, brand_id
LIMIT 100
