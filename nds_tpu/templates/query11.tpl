-- define [YEAR] = uniform_int(1998, 2001)
WITH year_total AS (
  SELECT c_customer_id AS customer_id,
         c_first_name AS customer_first_name,
         c_last_name AS customer_last_name,
         c_preferred_cust_flag AS customer_preferred_cust_flag,
         c_birth_country AS customer_birth_country,
         c_login AS customer_login,
         c_email_address AS customer_email_address,
         d_year AS dyear,
         SUM(ss_ext_list_price - ss_ext_discount_amt) AS year_total,
         's' AS sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
           c_birth_country, c_login, c_email_address, d_year
  UNION ALL
  SELECT c_customer_id AS customer_id,
         c_first_name AS customer_first_name,
         c_last_name AS customer_last_name,
         c_preferred_cust_flag AS customer_preferred_cust_flag,
         c_birth_country AS customer_birth_country,
         c_login AS customer_login,
         c_email_address AS customer_email_address,
         d_year AS dyear,
         SUM(ws_ext_list_price - ws_ext_discount_amt) AS year_total,
         'w' AS sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
           c_birth_country, c_login, c_email_address, d_year
)
SELECT t_s_secyear.customer_id,
       t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name,
       t_s_secyear.customer_email_address
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's'
  AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's'
  AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = [YEAR]
  AND t_s_secyear.dyear = [YEAR] + 1
  AND t_w_firstyear.dyear = [YEAR]
  AND t_w_secyear.dyear = [YEAR] + 1
  AND t_s_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
           THEN t_w_secyear.year_total / t_w_firstyear.year_total
           ELSE 0.0 END
      > CASE WHEN t_s_firstyear.year_total > 0
             THEN t_s_secyear.year_total / t_s_firstyear.year_total
             ELSE 0.0 END
ORDER BY t_s_secyear.customer_id,
         t_s_secyear.customer_first_name,
         t_s_secyear.customer_last_name,
         t_s_secyear.customer_email_address
LIMIT 100
