-- define [HOUR_AM] = uniform_int(6, 12)
-- define [HOUR_PM] = uniform_int(13, 21)
-- define [DEP] = uniform_int(0, 6)
SELECT CAST(amc AS DOUBLE) / CAST(pmc AS DOUBLE) AS am_pm_ratio
FROM (SELECT COUNT(*) AS amc
      FROM web_sales, household_demographics, time_dim, web_page
      WHERE ws_sold_time_sk = time_dim.t_time_sk
        AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        AND ws_web_page_sk = web_page.wp_web_page_sk
        AND time_dim.t_hour BETWEEN [HOUR_AM] AND [HOUR_AM] + 1
        AND household_demographics.hd_dep_count = [DEP]
        AND web_page.wp_char_count BETWEEN 5000 AND 5200) at_,
     (SELECT COUNT(*) AS pmc
      FROM web_sales, household_demographics, time_dim, web_page
      WHERE ws_sold_time_sk = time_dim.t_time_sk
        AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        AND ws_web_page_sk = web_page.wp_web_page_sk
        AND time_dim.t_hour BETWEEN [HOUR_PM] AND [HOUR_PM] + 1
        AND household_demographics.hd_dep_count = [DEP]
        AND web_page.wp_char_count BETWEEN 5000 AND 5200) pt
ORDER BY am_pm_ratio
LIMIT 100
