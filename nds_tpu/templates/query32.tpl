-- define [IMID] = uniform_int(1, 1000)
-- define [SDATE] = rand_date(1998, 2002)
SELECT SUM(cs_ext_discount_amt) AS excess_discount_amount
FROM catalog_sales, item, date_dim
WHERE i_manufact_id = [IMID]
  AND i_item_sk = cs_item_sk
  AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                 AND (CAST('[SDATE]' AS DATE) + INTERVAL 90 DAYS)
  AND d_date_sk = cs_sold_date_sk
  AND cs_ext_discount_amt >
      (SELECT 1.3 * AVG(cs_ext_discount_amt)
       FROM catalog_sales, date_dim
       WHERE cs_item_sk = i_item_sk
         AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                        AND (CAST('[SDATE]' AS DATE) + INTERVAL 90 DAYS)
         AND d_date_sk = cs_sold_date_sk)
LIMIT 100
