-- define [YEAR] = uniform_int(1998, 2002)
-- define [QOY] = uniform_int(1, 2)
-- define [ZIPS] = ziplist(50)
SELECT s_store_name, SUM(ss_net_profit) AS net_profit
FROM store_sales, date_dim, store,
     (SELECT ca_zip
      FROM (SELECT SUBSTR(ca_zip, 1, 5) AS ca_zip
            FROM customer_address
            WHERE SUBSTR(ca_zip, 1, 5) IN ([ZIPS])
            INTERSECT
            SELECT ca_zip
            FROM (SELECT SUBSTR(ca_zip, 1, 5) AS ca_zip, COUNT(*) AS cnt
                  FROM customer_address, customer
                  WHERE ca_address_sk = c_current_addr_sk
                    AND c_preferred_cust_flag = 'Y'
                  GROUP BY ca_zip
                  HAVING COUNT(*) > 1) a1) a2) v1
WHERE ss_store_sk = s_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_qoy = [QOY]
  AND d_year = [YEAR]
  AND SUBSTR(s_zip, 1, 2) = SUBSTR(v1.ca_zip, 1, 2)
GROUP BY s_store_name
ORDER BY s_store_name
LIMIT 100
