-- define [COLOR1] = choice('powder','orchid','peach','pale','metallic','lavender','maroon','misty')
-- define [COLOR2] = choice('chiffon','salmon','sandy','seashell','sienna','sky','slate','smoke')
WITH ssales AS (
  SELECT c_last_name, c_first_name, s_store_name, ca_state, s_state,
         i_color, i_current_price, i_manager_id, i_units, i_size,
         SUM(ss_net_paid) AS netpaid
  FROM store_sales, store_returns, store, item, customer, customer_address
  WHERE ss_ticket_number = sr_ticket_number
    AND ss_item_sk = sr_item_sk
    AND ss_customer_sk = c_customer_sk
    AND ss_item_sk = i_item_sk
    AND ss_store_sk = s_store_sk
    AND c_current_addr_sk = ca_address_sk
    AND c_birth_country <> UPPER(ca_country)
    AND s_zip = ca_zip
    AND s_market_id = 8
  GROUP BY c_last_name, c_first_name, s_store_name, ca_state, s_state,
           i_color, i_current_price, i_manager_id, i_units, i_size
)
SELECT c_last_name, c_first_name, s_store_name, SUM(netpaid) AS paid
FROM ssales
WHERE i_color = '[COLOR1]'
GROUP BY c_last_name, c_first_name, s_store_name
HAVING SUM(netpaid) > (SELECT 0.05 * AVG(netpaid) FROM ssales)
ORDER BY c_last_name, c_first_name, s_store_name;
WITH ssales AS (
  SELECT c_last_name, c_first_name, s_store_name, ca_state, s_state,
         i_color, i_current_price, i_manager_id, i_units, i_size,
         SUM(ss_net_paid) AS netpaid
  FROM store_sales, store_returns, store, item, customer, customer_address
  WHERE ss_ticket_number = sr_ticket_number
    AND ss_item_sk = sr_item_sk
    AND ss_customer_sk = c_customer_sk
    AND ss_item_sk = i_item_sk
    AND ss_store_sk = s_store_sk
    AND c_current_addr_sk = ca_address_sk
    AND c_birth_country <> UPPER(ca_country)
    AND s_zip = ca_zip
    AND s_market_id = 8
  GROUP BY c_last_name, c_first_name, s_store_name, ca_state, s_state,
           i_color, i_current_price, i_manager_id, i_units, i_size
)
SELECT c_last_name, c_first_name, s_store_name, SUM(netpaid) AS paid
FROM ssales
WHERE i_color = '[COLOR2]'
GROUP BY c_last_name, c_first_name, s_store_name
HAVING SUM(netpaid) > (SELECT 0.05 * AVG(netpaid) FROM ssales)
ORDER BY c_last_name, c_first_name, s_store_name
