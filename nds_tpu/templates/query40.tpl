-- define [SDATE] = rand_date(1998, 2002)
SELECT w_state, i_item_id,
       SUM(CASE WHEN d_date < CAST('[SDATE]' AS DATE)
                THEN cs_sales_price - COALESCE(cr_refunded_cash, 0)
                ELSE 0 END) AS sales_before,
       SUM(CASE WHEN d_date >= CAST('[SDATE]' AS DATE)
                THEN cs_sales_price - COALESCE(cr_refunded_cash, 0)
                ELSE 0 END) AS sales_after
FROM catalog_sales
     LEFT OUTER JOIN catalog_returns ON
         (cs_order_number = cr_order_number AND cs_item_sk = cr_item_sk),
     warehouse, item, date_dim
WHERE i_current_price BETWEEN 0.99 AND 1.49
  AND i_item_sk = cs_item_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN (CAST('[SDATE]' AS DATE) - INTERVAL 30 DAYS)
                 AND (CAST('[SDATE]' AS DATE) + INTERVAL 30 DAYS)
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
LIMIT 100
