-- define [DMS] = uniform_int(1176, 1224)
SELECT SUBSTR(w_warehouse_name, 1, 20) AS warehouse_name, sm_type, cc_name,
       SUM(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk <= 30)
                THEN 1 ELSE 0 END) AS days_30,
       SUM(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 30)
                 AND (cs_ship_date_sk - cs_sold_date_sk <= 60)
                THEN 1 ELSE 0 END) AS days_31_60,
       SUM(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 60)
                 AND (cs_ship_date_sk - cs_sold_date_sk <= 90)
                THEN 1 ELSE 0 END) AS days_61_90,
       SUM(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 90)
                 AND (cs_ship_date_sk - cs_sold_date_sk <= 120)
                THEN 1 ELSE 0 END) AS days_91_120,
       SUM(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 120)
                THEN 1 ELSE 0 END) AS days_over_120
FROM catalog_sales, warehouse, ship_mode, call_center, date_dim
WHERE d_month_seq BETWEEN [DMS] AND [DMS] + 11
  AND cs_ship_date_sk = d_date_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_ship_mode_sk = sm_ship_mode_sk
  AND cs_call_center_sk = cc_call_center_sk
GROUP BY SUBSTR(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY SUBSTR(w_warehouse_name, 1, 20), sm_type, cc_name
LIMIT 100
