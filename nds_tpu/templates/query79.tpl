-- define [YEAR] = uniform_int(1998, 2000)
-- define [DEP] = uniform_int(0, 6)
-- define [VEH] = uniform_int(-1, 4)
SELECT c_last_name, c_first_name, SUBSTR(s_city, 1, 30) AS city,
       ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, store.s_city,
             SUM(ss_coupon_amt) AS amt, SUM(ss_net_profit) AS profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        AND (household_demographics.hd_dep_count = [DEP]
             OR household_demographics.hd_vehicle_count > [VEH])
        AND date_dim.d_dow = 1
        AND date_dim.d_year IN ([YEAR], [YEAR] + 1, [YEAR] + 2)
        AND store.s_number_employees BETWEEN 200 AND 295
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, store.s_city) ms,
     customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, SUBSTR(s_city, 1, 30), profit
LIMIT 100
