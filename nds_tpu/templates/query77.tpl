-- define [SDATE] = rand_date(1998, 2002)
WITH ss AS (
  SELECT s_store_sk, SUM(ss_ext_sales_price) AS sales,
         SUM(ss_net_profit) AS profit
  FROM store_sales, date_dim, store
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                   AND (CAST('[SDATE]' AS DATE) + INTERVAL 30 DAYS)
    AND ss_store_sk = s_store_sk
  GROUP BY s_store_sk
),
sr AS (
  SELECT s_store_sk, SUM(sr_return_amt) AS returns_amt,
         SUM(sr_net_loss) AS profit_loss
  FROM store_returns, date_dim, store
  WHERE sr_returned_date_sk = d_date_sk
    AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                   AND (CAST('[SDATE]' AS DATE) + INTERVAL 30 DAYS)
    AND sr_store_sk = s_store_sk
  GROUP BY s_store_sk
),
cs AS (
  SELECT cs_call_center_sk, SUM(cs_ext_sales_price) AS sales,
         SUM(cs_net_profit) AS profit
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                   AND (CAST('[SDATE]' AS DATE) + INTERVAL 30 DAYS)
  GROUP BY cs_call_center_sk
),
cr AS (
  SELECT cr_call_center_sk, SUM(cr_return_amount) AS returns_amt,
         SUM(cr_net_loss) AS profit_loss
  FROM catalog_returns, date_dim
  WHERE cr_returned_date_sk = d_date_sk
    AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                   AND (CAST('[SDATE]' AS DATE) + INTERVAL 30 DAYS)
  GROUP BY cr_call_center_sk
),
ws AS (
  SELECT wp_web_page_sk, SUM(ws_ext_sales_price) AS sales,
         SUM(ws_net_profit) AS profit
  FROM web_sales, date_dim, web_page
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                   AND (CAST('[SDATE]' AS DATE) + INTERVAL 30 DAYS)
    AND ws_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk
),
wr AS (
  SELECT wp_web_page_sk, SUM(wr_return_amt) AS returns_amt,
         SUM(wr_net_loss) AS profit_loss
  FROM web_returns, date_dim, web_page
  WHERE wr_returned_date_sk = d_date_sk
    AND d_date BETWEEN CAST('[SDATE]' AS DATE)
                   AND (CAST('[SDATE]' AS DATE) + INTERVAL 30 DAYS)
    AND wr_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk
)
SELECT channel, id, SUM(sales) AS sales, SUM(returns_amt) AS returns_amt,
       SUM(profit) AS profit
FROM (SELECT 'store channel' AS channel, ss.s_store_sk AS id, sales,
             COALESCE(returns_amt, 0) AS returns_amt,
             profit - COALESCE(profit_loss, 0) AS profit
      FROM ss LEFT JOIN sr ON ss.s_store_sk = sr.s_store_sk
      UNION ALL
      SELECT 'catalog channel' AS channel, cs_call_center_sk AS id, sales,
             returns_amt, profit - profit_loss AS profit
      FROM cs, cr
      UNION ALL
      SELECT 'web channel' AS channel, ws.wp_web_page_sk AS id, sales,
             COALESCE(returns_amt, 0) AS returns_amt,
             profit - COALESCE(profit_loss, 0) AS profit
      FROM ws LEFT JOIN wr ON ws.wp_web_page_sk = wr.wp_web_page_sk) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
