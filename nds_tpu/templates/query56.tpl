-- define [YEAR] = uniform_int(1998, 2002)
-- define [MONTH] = uniform_int(1, 12)
-- define [COLORS] = choice_n(3, 'almond','antique','aquamarine','azure','beige','bisque','black','blanched','blue','blush','brown')
WITH ss AS (
  SELECT i_item_id, SUM(ss_ext_sales_price) AS total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item WHERE i_color IN ([COLORS]))
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = [YEAR]
    AND d_moy = [MONTH]
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id
),
cs AS (
  SELECT i_item_id, SUM(cs_ext_sales_price) AS total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item WHERE i_color IN ([COLORS]))
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = [YEAR]
    AND d_moy = [MONTH]
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id
),
ws AS (
  SELECT i_item_id, SUM(ws_ext_sales_price) AS total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item WHERE i_color IN ([COLORS]))
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = [YEAR]
    AND d_moy = [MONTH]
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id
)
SELECT i_item_id, SUM(total_sales) AS total_sales
FROM (SELECT * FROM ss
      UNION ALL
      SELECT * FROM cs
      UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales, i_item_id
LIMIT 100
