-- define [DEP1] = uniform_int(-1, 4)
-- define [DEP2] = uniform_int(-1, 4)
-- define [DEP3] = uniform_int(-1, 4)
-- define [STORE] = choice('ought','able','pri','ese','anti','cally','ation','eing','n st','bar')
SELECT *
FROM (SELECT COUNT(*) AS h8_30_to_9
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 8
        AND time_dim.t_minute >= 30
        AND ((household_demographics.hd_dep_count = [DEP1]
              AND household_demographics.hd_vehicle_count <= [DEP1] + 2)
             OR (household_demographics.hd_dep_count = [DEP2]
                 AND household_demographics.hd_vehicle_count <= [DEP2] + 2)
             OR (household_demographics.hd_dep_count = [DEP3]
                 AND household_demographics.hd_vehicle_count <= [DEP3] + 2))
        AND store.s_store_name = '[STORE]') s1,
     (SELECT COUNT(*) AS h9_to_9_30
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 9
        AND time_dim.t_minute < 30
        AND ((household_demographics.hd_dep_count = [DEP1]
              AND household_demographics.hd_vehicle_count <= [DEP1] + 2)
             OR (household_demographics.hd_dep_count = [DEP2]
                 AND household_demographics.hd_vehicle_count <= [DEP2] + 2)
             OR (household_demographics.hd_dep_count = [DEP3]
                 AND household_demographics.hd_vehicle_count <= [DEP3] + 2))
        AND store.s_store_name = '[STORE]') s2,
     (SELECT COUNT(*) AS h9_30_to_10
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 9
        AND time_dim.t_minute >= 30
        AND ((household_demographics.hd_dep_count = [DEP1]
              AND household_demographics.hd_vehicle_count <= [DEP1] + 2)
             OR (household_demographics.hd_dep_count = [DEP2]
                 AND household_demographics.hd_vehicle_count <= [DEP2] + 2)
             OR (household_demographics.hd_dep_count = [DEP3]
                 AND household_demographics.hd_vehicle_count <= [DEP3] + 2))
        AND store.s_store_name = '[STORE]') s3,
     (SELECT COUNT(*) AS h10_to_10_30
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 10
        AND time_dim.t_minute < 30
        AND ((household_demographics.hd_dep_count = [DEP1]
              AND household_demographics.hd_vehicle_count <= [DEP1] + 2)
             OR (household_demographics.hd_dep_count = [DEP2]
                 AND household_demographics.hd_vehicle_count <= [DEP2] + 2)
             OR (household_demographics.hd_dep_count = [DEP3]
                 AND household_demographics.hd_vehicle_count <= [DEP3] + 2))
        AND store.s_store_name = '[STORE]') s4,
     (SELECT COUNT(*) AS h10_30_to_11
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 10
        AND time_dim.t_minute >= 30
        AND ((household_demographics.hd_dep_count = [DEP1]
              AND household_demographics.hd_vehicle_count <= [DEP1] + 2)
             OR (household_demographics.hd_dep_count = [DEP2]
                 AND household_demographics.hd_vehicle_count <= [DEP2] + 2)
             OR (household_demographics.hd_dep_count = [DEP3]
                 AND household_demographics.hd_vehicle_count <= [DEP3] + 2))
        AND store.s_store_name = '[STORE]') s5,
     (SELECT COUNT(*) AS h11_to_11_30
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 11
        AND time_dim.t_minute < 30
        AND ((household_demographics.hd_dep_count = [DEP1]
              AND household_demographics.hd_vehicle_count <= [DEP1] + 2)
             OR (household_demographics.hd_dep_count = [DEP2]
                 AND household_demographics.hd_vehicle_count <= [DEP2] + 2)
             OR (household_demographics.hd_dep_count = [DEP3]
                 AND household_demographics.hd_vehicle_count <= [DEP3] + 2))
        AND store.s_store_name = '[STORE]') s6,
     (SELECT COUNT(*) AS h11_30_to_12
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 11
        AND time_dim.t_minute >= 30
        AND ((household_demographics.hd_dep_count = [DEP1]
              AND household_demographics.hd_vehicle_count <= [DEP1] + 2)
             OR (household_demographics.hd_dep_count = [DEP2]
                 AND household_demographics.hd_vehicle_count <= [DEP2] + 2)
             OR (household_demographics.hd_dep_count = [DEP3]
                 AND household_demographics.hd_vehicle_count <= [DEP3] + 2))
        AND store.s_store_name = '[STORE]') s7,
     (SELECT COUNT(*) AS h12_to_12_30
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 12
        AND time_dim.t_minute < 30
        AND ((household_demographics.hd_dep_count = [DEP1]
              AND household_demographics.hd_vehicle_count <= [DEP1] + 2)
             OR (household_demographics.hd_dep_count = [DEP2]
                 AND household_demographics.hd_vehicle_count <= [DEP2] + 2)
             OR (household_demographics.hd_dep_count = [DEP3]
                 AND household_demographics.hd_vehicle_count <= [DEP3] + 2))
        AND store.s_store_name = '[STORE]') s8
