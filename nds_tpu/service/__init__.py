"""Concurrent query service: admission-controlled async execution over one
shared Session/device mesh (ROADMAP item 4 — the interactive multi-user
shape "Accelerating Presto with GPUs" demonstrates, PAPERS.md).

Public surface:

- :class:`QueryService` — the long-lived in-process service: bounded
  admission queue, planner worker threads overlapping host-side parse/plan
  with device execution, a single device lane, and capacity-ladder-aware
  batching of compatible parameterized plans.
- :class:`ServiceConfig` — admission limits, worker counts, per-tenant
  deadlines, batching knobs.
- :class:`Ticket` — one submitted query's async handle (``result()``).
- typed failures: :class:`~nds_tpu.resilience.AdmissionRejected` (queue
  full / closed), :class:`~nds_tpu.resilience.DeadlineExceeded`
  (per-tenant deadline expired while queued / lane watchdog abandon), and
  :class:`~nds_tpu.resilience.CircuitOpen` (a per-error-class breaker is
  shedding load until a half-open probe succeeds).

Self-healing (all opt-in via ServiceConfig, exercised by ``nds_tpu/chaos``
campaigns): circuit breaker at admission, bounded transient-failure retry
budget, compiled-program quarantine, and a device-lane watchdog.

Distributed serving (``service/frontdoor.py``, all opt-in):

- :class:`FrontDoorServer` — the Arrow-IPC wire front door: N client
  PROCESSES submit SQL + tenant + deadline to one engine process over a
  stdlib socket; serialization runs on per-connection threads off the
  device lane; admission/breakers/deadlines/batching reused unchanged.
- :class:`FlightClient` — the thin synchronous client (persistent
  connection, typed-error reconstruction, bounded reconnect-retry, and
  an optional snapshot-warmed local result cache with a per-use
  invalidation handshake).
- :class:`ConnectionDropped` / :class:`RemoteQueryError` — the wire
  layer's typed failures (transient / unknown-remote-class).
"""
from ..engine.result_cache import ResultCache, ResultCacheConfig
from ..resilience import (AdmissionRejected, CircuitBreakerConfig,
                          CircuitOpen, DeadlineExceeded)
from .frontdoor import (ConnectionDropped, FlightClient, FrontDoorServer,
                        RemoteQueryError)
from .service import QueryService, ServiceConfig, Ticket

__all__ = ["QueryService", "ServiceConfig", "Ticket", "AdmissionRejected",
           "CircuitBreakerConfig", "CircuitOpen", "DeadlineExceeded",
           "ResultCache", "ResultCacheConfig", "FrontDoorServer",
           "FlightClient", "ConnectionDropped", "RemoteQueryError"]
