"""The in-process concurrent query service.

Everything the engine measured before this module was batch-shaped: one
caller per Session, one query at a time, the device idle between a query's
host merge and the next query's staging. The service converts that into a
concurrency contract across the existing layers:

- **Admission control** (``submit``): a bounded pending count — overload
  raises a typed :class:`~nds_tpu.resilience.AdmissionRejected` at the
  door instead of piling queries up behind the accelerator. Per-tenant
  wall-clock budgets map onto :class:`~nds_tpu.resilience.Deadline`; a
  query whose budget expires while queued fails typed
  (:class:`~nds_tpu.resilience.DeadlineExceeded`) while its neighbors
  complete.
- **Pipelined scheduling**: planner worker threads parse/plan/parameterize
  queued queries (pure host-side Python) CONCURRENTLY with the device
  lane executing earlier queries — XLA dispatch releases the GIL, so one
  query's planning genuinely overlaps another's device execution. A
  cross-client plan cache keyed by SQL text + the session's streaming
  config fingerprint means repeated dashboard-style texts plan once.
- **Shared program cache**: execution reuses the session's JaxExecutor and
  the process-wide ``_SHARED_PROGRAMS`` registry (cross-stream adoption by
  parameterized-plan fingerprint, PERF.md round 5) — the Nth client
  running a template re-traces and re-compiles NOTHING, whichever client
  compiled first.
- **Compatible-plan batching**: ready queries that parameterize to the
  same plan fingerprint are served through ONE compiled program over a
  stacked parameter matrix (``executor.BatchedQuery``: ``lax.map`` over
  the capacity-ladder-padded batch; parameter-identical duplicates
  deduplicate to a single row). Row i's computation graph is exactly the
  single-query program's, so results are bit-identical to serial
  execution; any schedule drift falls the batch back to the normal
  record/replay path.

The device lane is ONE thread: the accelerator executes one program at a
time anyway, and a single lane keeps the session executor's state
single-writer (Session serializes statements on ``_sql_lock`` for safety,
so even direct ``session.sql`` callers stay correct beside the service).

- **Semantic result cache** (opt-in, ``ServiceConfig.result_cache`` /
  ``EngineConfig.result_cache``): repeat texts are answered at ADMISSION
  from the cross-client result cache (no planner thread, no device
  lane); first-sighting texts of a cached template and provably-narrower
  filters are answered at the planner stage (exact-by-fingerprint and
  subsumption tiers of ``engine/result_cache.py``); maintenance deltas
  UPDATE cached mergeable aggregates in place instead of invalidating.

**Self-healing** (opt-in via ServiceConfig; chaos campaigns in
``nds_tpu/chaos`` exercise all four): a per-error-class circuit breaker
at admission (typed ``CircuitOpen`` until a half-open probe succeeds), a
bounded retry budget re-dispatching transient ticket failures off the
device lane, quarantine of shared compiled programs that fail repeatedly
(evicted + re-recorded instead of poisoning every adopter), and a
device-lane watchdog that abandons a wedged dispatch and swaps fresh
session locks the way the power runner recovers from a deadline kill.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional

from ..obs import metrics as _metrics
from ..obs.flight import FLIGHT
from ..obs.stats import ExecStats
from ..obs.trace import TRACER
from ..resilience import (AdmissionRejected, CircuitBreaker,
                          CircuitBreakerConfig, CircuitOpen, Deadline,
                          DeadlineExceeded, RetryPolicy, run_with_deadline)


def _observe_phase(name: str, ms: float, tenant: str,
                   template: Optional[str]) -> None:
    """Record one phase wall into its histogram family: the base series
    (whole-service view) plus the (tenant, template) child, so per-tenant
    p50/p95/p99 and top-K slow templates read live from the registry."""
    _metrics.METRICS.histogram(name).observe(ms)
    if template:
        _metrics.METRICS.histogram(name, tenant=tenant,
                                   template=template).observe(ms)


class ServiceClosed(AdmissionRejected):
    """Submitted to a service that is not running (never started, closing,
    or closed) — a typed admission failure, retryable against a restarted
    service."""


@dataclass
class ServiceConfig:
    """Knobs of one QueryService instance (engine knobs stay on
    EngineConfig — the service composes a Session, it does not own one)."""
    #: admitted-but-unfinished queries the service holds before refusing
    #: new work (typed AdmissionRejected). The pressure valve: clients see
    #: overload immediately and back off instead of stacking latency.
    max_pending: int = 256
    #: planner worker threads (parse/plan/parameterize). Host-side Python:
    #: more than a few buys little under the GIL, but >= 2 keeps planning
    #: flowing while one worker waits on cold column-stats reads.
    plan_workers: int = 2
    #: default per-query wall budget in seconds (0 = unbounded), measured
    #: from ADMISSION — queue wait spends the budget, so an overloaded
    #: service sheds stale work instead of executing it late.
    default_deadline_s: float = 0.0
    #: per-tenant deadline overrides: {tenant: seconds}
    tenant_deadlines: dict = field(default_factory=dict)
    #: serve compatible parameterized plans through one batched dispatch
    batching: bool = True
    #: most queries coalesced into one batched dispatch (the stacked
    #: parameter matrix pads to the capacity ladder above this count's
    #: bucket, so the knob also bounds compiled batch shapes)
    max_batch: int = 16
    #: after the first ready query is picked up, wait this long for more
    #: compatible arrivals before dispatching (0 = serve whatever is
    #: already queued; open-loop load keeps the queue nonempty by itself)
    batch_linger_ms: float = 0.0
    #: cross-client plan-cache entries (SQL text -> planned query); LRU
    plan_cache_entries: int = 512
    # -- self-healing (chaos-hardened serving; all off by default so a
    #    plain service behaves exactly as before) -------------------------
    #: per-error-class circuit breaker at admission: a failure class
    #: crossing its windowed rate trips, new submits fail typed
    #: CircuitOpen, half-open probes test recovery (None = disabled)
    breaker: Optional[CircuitBreakerConfig] = None
    #: service-lifetime budget of transient ticket failures re-dispatched
    #: off the device lane (requeued at the back of the ready queue)
    #: instead of failing the client; 0 disables
    retry_budget: int = 0
    #: dispatch attempts per ticket while the retry budget lasts
    ticket_attempts: int = 2
    #: device-lane watchdog: a serial dispatch exceeding this wall budget
    #: is ABANDONED mid-flight (fresh session locks swap in, the way
    #: power.py recovers from a deadline kill) and the ticket fails typed
    #: DeadlineExceeded while the lane serves its neighbors; 0 disables
    dispatch_timeout_s: float = 0.0
    #: strike shared compiled programs on batched-dispatch failures and
    #: evict them after executor.QUARANTINE_STRIKES (re-recorded fresh on
    #: next use instead of poisoning every adopter)
    quarantine: bool = True
    # -- weighted-fair scheduling + morsel-boundary preemption (all off
    #    by default: the plain service keeps the FIFO ready queue and
    #    never installs the session preemption hook — bit-identical to
    #    before these knobs existed) --------------------------------------
    #: replace the FIFO ready queue with per-tenant weighted-fair queues
    #: (virtual-time WFQ): each tenant accrues virtual time at
    #: cost/weight per second of device lane consumed, and the lane
    #: always serves the least-served active tenant next — a saturating
    #: batch tenant can no longer convoy an interactive tenant's queue
    fair_queue: bool = False
    #: relative service weights per tenant {tenant: weight}; unlisted
    #: tenants weigh 1.0 (higher weight = larger device-lane share)
    tenant_weights: dict = field(default_factory=dict)
    #: let streamed dispatches YIELD the device lane at morsel/scan-group
    #: boundaries: the session calls back into the service between scan
    #: groups, non-streamed ready tickets run right there on the lane
    #: thread (the stream's cached state resumes untouched — responses
    #: stay bit-identical to serial execution), then the scan continues
    preemption: bool = False
    #: most tickets served per yield point (bounds how long one morsel
    #: boundary can hold the stream)
    preempt_max: int = 2
    #: in-flight dedup at the planner stage: a ticket whose (fingerprint,
    #: params, catalog generation, snapshot version) matches an already-
    #: admitted in-flight ticket parks on that leader's shared result
    #: cell instead of re-entering the ready queue — the leader executes
    #: once, followers attach (service_inflight_dedup counts them)
    inflight_dedup: bool = False
    #: semantic result cache (engine/result_cache.ResultCacheConfig):
    #: exact cross-client reuse at ADMISSION (a repeat dashboard text
    #: touches neither planner thread nor device lane), subsumption
    #: proofs at the planner stage, and IVM across maintenance deltas.
    #: None falls back to the session's EngineConfig.result_cache flag
    #: (still-None/off = no cache, the pre-cache service exactly).
    result_cache: Optional[object] = None
    #: live scrape endpoint (obs/scrape.MetricsServer): serve /metrics
    #: (Prometheus exposition), /healthz, and /query?sql=SELECT... over
    #: the system.* tables for the service's lifetime. None = off;
    #: 0 = an OS-assigned ephemeral port (tests; the bound port reads
    #: back from QueryService.metrics_server.port)
    metrics_port: Optional[int] = None
    #: bind address for the scrape endpoint (loopback by default: the
    #: wire surface is an operator tool, not an authenticated API)
    metrics_host: str = "127.0.0.1"


class Ticket:
    """One submitted query's handle. The service hands the ticket through
    its stages (admission -> planner worker -> device lane); each stage is
    the ticket's sole owner while it holds it, and ``result()`` is the
    client-side rendezvous.

    The ticket is also the trace-context carrier: ``root`` is a detached
    ``service/ticket`` span opened at admission on the client thread and
    closed at completion on whichever thread finishes the ticket, and
    ``trace_id`` (= root span id, 0 when tracing is disabled) joins the
    ticket's :class:`ExecStats` to its span subtree in an export. Stage
    spans (queue/plan/lane_wait/dispatch/materialize) parent-link to it
    across the three thread hops."""

    def __init__(self, query: str, label: str, tenant: str,
                 deadline: Deadline, backend: Optional[str]):
        self.query = query
        self.label = label
        self.tenant = tenant
        self.deadline = deadline
        self.backend = backend
        self.submitted_at = time.perf_counter()
        #: wall between admission and execution start (ms); lands in stats
        self.queue_wait_ms: Optional[float] = None
        #: per-stage walls for the ticket's query-log row (obs/query_log)
        self.plan_ms: Optional[float] = None
        self.exec_ms: Optional[float] = None
        #: per-query ExecStats (queue_wait_ms/batched_with/trace_id incl.)
        self.stats: Optional[ExecStats] = None
        # trace context (set by the service at admission)
        self.root = None                    # detached service/ticket span
        self.trace_id: int = 0
        self._queue_span = None             # admission -> planner pickup
        self._wait_span = None              # planned -> execution start
        #: template identity for SLO labels: the parameterized-plan
        #: fingerprint when one exists (instantiations of one template
        #: collapse), else the stable query label
        self.template: Optional[str] = None
        # planner-stage products
        self.plan = None
        self.fp: Optional[str] = None
        self.pvalues: tuple = ()
        self.use_jax = True
        #: planner verdict: the plan takes the streamed morsel path —
        #: streamed tickets are never chosen as preemptors (they would
        #: hold the lane for a whole scan at the yield point) and carry
        #: the yield points themselves
        self.streams = False
        #: tickets served at THIS dispatch's morsel-boundary yield points
        #: (nonzero only for streamed dispatches under preemption; lands
        #: in the ticket's query-log row)
        self.preempted = 0
        #: in-flight dedup: the leader's registry key while it owns one,
        #: and the follower tickets parked on its result cell
        self._dedup_key = None
        self._dedup_followers: list = []
        #: serial dispatch attempts (the retry budget requeues transient
        #: failures until this reaches ServiceConfig.ticket_attempts)
        self.attempts = 0
        #: error-class name this ticket probes for a half-open breaker
        self._probe: Optional[str] = None
        self._done = threading.Event()
        self._result = None
        self._materialize = None
        self._mat_lock = threading.Lock()
        self._error: Optional[BaseException] = None

    # -- stage transitions (methods so stage loops stay lint-clean:
    #    single-owner handoff, no shared-state writes in thread targets) --
    def set_planned(self, plan, fp, pvalues, use_jax,
                    streams: bool = False) -> None:
        self.plan = plan
        self.fp = fp
        self.pvalues = tuple(pvalues)
        self.use_jax = use_jax
        self.streams = streams
        self.template = fp[:12] if fp else self.label

    def picked_up(self) -> None:
        """A planner worker took the ticket: the admission-queue span
        ends here (single-owner handoff, so no lock needed)."""
        if self._queue_span is not None:
            self._queue_span.end()
            self._queue_span = None

    def begin_wait(self) -> None:
        """Planned; now waiting for the device lane (span ends at
        mark_started / expiry)."""
        self._wait_span = TRACER.span(
            "service/lane_wait", cat="service", parent=self.trace_id,
            label=self.label).begin()

    def mark_started(self) -> float:
        """Execution starts now: record + return the queue wait (ms)."""
        if self._wait_span is not None:
            self._wait_span.end()
            self._wait_span = None
        self.queue_wait_ms = round(
            (time.perf_counter() - self.submitted_at) * 1000.0, 3)
        _observe_phase("service_queue_wait_ms", self.queue_wait_ms,
                       self.tenant, self.template)
        return self.queue_wait_ms

    def close_stage_spans(self, error: Optional[str] = None) -> None:
        """End any stage span still open (expiry/failure can strike while
        queued or while waiting for the lane)."""
        for name in ("_queue_span", "_wait_span"):
            sp = getattr(self, name)
            if sp is not None:
                sp.end(error=error)
                setattr(self, name, None)

    def finish(self, result, stats: Optional[ExecStats],
               materialize=None) -> None:
        """materialize: optional deferred host-side conversion applied in
        result() on the CLIENT's thread — the device lane hands out raw
        per-row outputs and N clients materialize their Tables in
        parallel instead of serializing that work behind the lane."""
        self._result = result
        self._materialize = materialize
        self.stats = stats
        self._done.set()

    def fail(self, error: BaseException) -> None:
        self._error = error
        self._done.set()

    # -- client side ---------------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until the query finishes; returns its Table or raises the
        typed failure (AdmissionRejected subclasses are raised by submit()
        itself — here land DeadlineExceeded, parse/plan/execution errors).
        Tables are READ-ONLY: parameter-identical queries served by one
        batched row share the same materialized object."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.label!r} not finished within {timeout}s")
        if self._error is not None:
            raise self._error
        with self._mat_lock:
            if self._materialize is not None:
                t0 = time.perf_counter()
                with TRACER.span("service/materialize", cat="service",
                                 parent=self.trace_id, label=self.label):
                    self._result = self._materialize(self._result)
                self._materialize = None
                _observe_phase(
                    "service_materialize_ms",
                    (time.perf_counter() - t0) * 1000.0,
                    self.tenant, self.template)
        return self._result


class _PlannedQuery:
    """Cross-client plan-cache entry for one SQL text."""
    __slots__ = ("plan", "fp", "pvalues", "streams")

    def __init__(self, plan, fp, pvalues, streams):
        self.plan = plan
        self.fp = fp
        self.pvalues = tuple(pvalues)
        self.streams = streams


class _FairReadyQueue:
    """Per-tenant weighted-fair ready queue (virtual-time WFQ).

    Each tenant keeps a FIFO of its own tickets plus a virtual time that
    advances by ``cost / weight`` whenever the device lane charges it
    (``charge``); ``popleft`` always serves the head of the least-served
    active tenant, ties broken by activation order — so a tenant with
    weight 2 earns twice the lane share of a weight-1 tenant, and an
    interactive tenant that shows up mid-saturation is served after at
    most one in-flight dispatch instead of behind the whole backlog.

    A tenant REACTIVATING after idle resumes at the current virtual
    floor, never below it: sleeping earns no credit (no post-idle burst)
    and costs none (no starvation).

    Deque-compatible surface (append/popleft/clear/len/iter/bool): every
    existing consumer of the FIFO ready deque — the lane drain, requeue,
    close()'s drop sweep, the metrics-gate depth probe — works unchanged.
    All methods are called under the service's ``_cv`` lock."""

    def __init__(self, weights: Optional[dict] = None):
        self._weights = dict(weights or {})
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._vtime: dict = {}        # tenant -> accrued virtual time
        self._floor = 0.0             # vtime of the last tenant served

    def _weight(self, tenant: str) -> float:
        try:
            w = float(self._weights.get(tenant, 1.0))
        except (TypeError, ValueError):
            w = 1.0
        return w if w > 0 else 1e-6

    def append(self, ticket) -> None:
        q = self._queues.get(ticket.tenant)
        if q is None:
            q = self._queues[ticket.tenant] = deque()
        if not q:
            # (re)activation: join at the floor, keeping whatever debt
            # the tenant already accrued above it
            self._vtime[ticket.tenant] = max(
                self._vtime.get(ticket.tenant, 0.0), self._floor)
        q.append(ticket)

    def _pick(self) -> Optional[str]:
        best, best_v = None, None
        for tenant, q in self._queues.items():
            if not q:
                continue
            v = self._vtime.get(tenant, 0.0)
            if best is None or v < best_v:
                best, best_v = tenant, v
        return best

    def popleft(self):
        tenant = self._pick()
        if tenant is None:
            raise IndexError("pop from an empty ready queue")  # lint: typed-error-exempt (deque-API contract: callers pop only after a non-empty check under _cv — this precondition error never reaches a client)
        return self._take(tenant, 0)

    def pop_preemptable(self):
        """First NON-STREAMED ticket in fair order, or None: the yield
        point serves short in-core tickets only — a streamed preemptor
        would hold the paused stream for a whole scan."""
        for tenant in sorted(self._queues,
                             key=lambda t: self._vtime.get(t, 0.0)):
            for i, ticket in enumerate(self._queues[tenant]):
                if not ticket.streams:
                    return self._take(tenant, i)
        return None

    def _take(self, tenant: str, i: int):
        q = self._queues[tenant]
        ticket = q[i]
        del q[i]
        if not q:
            del self._queues[tenant]
        self._floor = max(self._floor, self._vtime.get(tenant, 0.0))
        return ticket

    def charge(self, tenant: str, cost_s: float) -> None:
        """Account ``cost_s`` seconds of device lane to ``tenant``."""
        self._vtime[tenant] = (self._vtime.get(tenant, 0.0)
                               + max(0.0, cost_s) / self._weight(tenant))

    def clear(self) -> None:
        self._queues.clear()

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def __bool__(self) -> bool:
        return any(self._queues.values())

    def __iter__(self):
        for q in self._queues.values():
            yield from q


class QueryService:
    """Long-lived async query service over one shared Session.

    Usage::

        svc = QueryService(session)           # or ServiceConfig(...)
        with svc:                             # start()/close()
            t = svc.submit("SELECT ...", tenant="dash", label="q1")
            table = t.result()
            # or synchronously:
            table = svc.sql("SELECT ...")

    Registrations should be quiesced while the service is running (the
    catalog generation invalidates caches correctly, but a registration
    racing an in-flight plan can produce a stale-plan failure the client
    must retry)."""

    def __init__(self, session, config: Optional[ServiceConfig] = None):
        self.session = session
        self.config = config or ServiceConfig()
        self._cv = threading.Condition()
        self._intake: deque = deque()     # admitted, awaiting planning
        # planned, awaiting the device lane: FIFO deque by default;
        # fair_queue swaps in the per-tenant weighted-fair queue (same
        # surface — every drain/requeue/probe site works on either)
        self._ready = _FairReadyQueue(self.config.tenant_weights) \
            if self.config.fair_queue else deque()
        self._pending = 0                 # admitted but unfinished
        #: in-flight dedup registry: dedup key -> leader ticket
        self._inflight: dict = {}
        #: tickets served at yield points since the CURRENT outer
        #: streamed dispatch began (single-writer: the thread running
        #: the outer dispatch is the thread its yield points run on)
        self._preempt_served = 0
        self._plan_cache: "OrderedDict" = OrderedDict()
        self._plan_cache_key = None       # config/generation fingerprint
        self._hold = False                # test/drain hook: park the lane
        self._running = False
        self._threads: list[threading.Thread] = []
        #: the live scrape endpoint (ServiceConfig.metrics_port); its
        #: bound port reads back from metrics_server.port once started
        self.metrics_server = None
        cfg = self.config
        self._breaker = CircuitBreaker(cfg.breaker) \
            if cfg.breaker is not None else None
        self._retry_budget_left = max(0, cfg.retry_budget)
        self._retry_policy = RetryPolicy()   # classification only
        # semantic result cache: explicit ServiceConfig object wins, else
        # the session's EngineConfig.result_cache flag arms the engine-
        # configured tiers; attached to the session so maintenance DML
        # publishes LF_*/DF_* deltas into it (IVM)
        rc_cfg = cfg.result_cache
        if rc_cfg is None and getattr(session.config, "result_cache",
                                      False):
            from ..engine.result_cache import ResultCacheConfig
            rc_cfg = ResultCacheConfig.from_engine(session.config)
        self.result_cache = None
        if rc_cfg is not None:
            from ..engine.result_cache import ResultCache
            self.result_cache = ResultCache(session, rc_cfg)
            session.attach_result_cache(self.result_cache)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "QueryService":
        with self._cv:
            if self._running:
                return self
            self._running = True
        n = max(1, self.config.plan_workers)
        self._threads = [
            threading.Thread(target=self._plan_worker, daemon=True,
                             name=f"svc-planner-{i}") for i in range(n)
        ] + [threading.Thread(target=self._device_loop, daemon=True,
                              name="svc-device-lane")]
        for t in self._threads:
            t.start()
        if self.config.preemption:
            # the streamed path's morsel-boundary yield points call back
            # into this service (Session._maybe_preempt); installing the
            # hook is what arms them — no hook, no behavior change
            self.session._preempt_hook = self._preempt_tick
        if self.config.metrics_port is not None \
                and self.metrics_server is None:
            # live scrape endpoint for the service's lifetime: /metrics,
            # /healthz, /query?sql=... over system.* (obs/scrape.py)
            from ..obs.scrape import MetricsServer
            self.metrics_server = MetricsServer(
                session=self.session, port=self.config.metrics_port,
                host=self.config.metrics_host).start()
        return self

    def close(self, drain: bool = True) -> None:
        """Stop the service. drain=True (default) finishes admitted work
        first; drain=False fails queued-but-unstarted tickets typed."""
        with self._cv:
            if not self._running:
                return
            if drain:
                while self._pending > 0:
                    self._cv.wait(0.05)
            self._running = False
            dropped = list(self._intake) + list(self._ready)
            self._intake.clear()
            self._ready.clear()
            self._cv.notify_all()
        for t in dropped:
            self._finish_ticket(t, error=ServiceClosed(
                f"service closed before {t.label!r} executed"))
        for t in self._threads:
            t.join(timeout=10)
        self._threads = []
        if self.session._preempt_hook == self._preempt_tick:
            self.session._preempt_hook = None
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        fb = getattr(self.session, "_feedback", None)
        if fb is not None:
            # persist observations accumulated since the last periodic
            # flush — the next attach loads them (adaptive warm start)
            fb.flush()

    def __enter__(self) -> "QueryService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close(drain=not any(exc))

    @contextlib.contextmanager
    def hold_dispatch(self):
        """Park the device lane (planning continues): deterministic batch
        accumulation for tests and drain windows."""
        with self._cv:
            self._hold = True
        try:
            yield
        finally:
            with self._cv:
                self._hold = False
                self._cv.notify_all()

    # -- admission -----------------------------------------------------------
    def submit(self, query: str, label: Optional[str] = None,
               tenant: str = "default",
               deadline_s: Optional[float] = None,
               backend: Optional[str] = None) -> Ticket:
        """Admit one query; returns its Ticket immediately.

        Raises AdmissionRejected (typed, with depth/limit) when the bounded
        pending set is full or the service is closed — overload is an
        immediate, classifiable signal, never a silent pile-up. The
        query's deadline (explicit > tenant override > default) starts
        NOW: queue wait spends it."""
        cfg = self.config
        if deadline_s is None:
            deadline_s = cfg.tenant_deadlines.get(
                tenant, cfg.default_deadline_s)
        ticket = Ticket(query, label or self._auto_label(query), tenant,
                        Deadline(deadline_s), backend)
        if "system." in query or "SYSTEM." in query:
            # system.* introspection bypass: observability must answer
            # DURING overload and open circuits, so the statement routes
            # around the breaker gate, the bounded pending set, the
            # planner workers, and the device lane entirely — it runs
            # host-only over registry snapshots on the CALLER's thread
            # (Session.system_query; zero admission/queue/dispatch
            # counters move, pinned by tests)
            done = self._try_system(ticket)
            if done is not None:
                return done
        if self._breaker is not None:
            # breaker gate BEFORE the pending set: a tripped class sheds
            # load at the door (typed, fatal-until-probe) so the queue
            # holds work that can actually succeed
            try:
                ticket._probe = self._breaker.admit(label=ticket.label)
            except CircuitOpen as e:
                _metrics.SERVICE_REJECTED.inc()
                FLIGHT.record("reject", label=ticket.label, tenant=tenant,
                              reason="circuit_open",
                              error_class=e.error_class)
                raise
        with self._cv:
            if not self._running:
                _metrics.SERVICE_REJECTED.inc()
                FLIGHT.record("reject", label=ticket.label, tenant=tenant,
                              reason="closed")
                if self._breaker is not None:
                    self._breaker.release(ticket._probe)
                raise ServiceClosed("query service is not running")
            if self._pending >= cfg.max_pending:
                _metrics.SERVICE_REJECTED.inc()
                FLIGHT.record("reject", label=ticket.label, tenant=tenant,
                              reason="queue_full", depth=self._pending,
                              limit=cfg.max_pending)
                if self._breaker is not None:
                    self._breaker.release(ticket._probe)
                raise AdmissionRejected(
                    f"admission queue full: {self._pending} pending >= "
                    f"max_pending {cfg.max_pending}",
                    depth=self._pending, limit=cfg.max_pending)
            self._pending += 1
            depth = self._pending
            _metrics.SERVICE_ADMITTED.inc()
            _metrics.SERVICE_QUEUE_DEPTH.set(self._pending)
            # the ticket's trace context: a detached root span the three
            # downstream thread hops (planner worker, device lane, client
            # materialization) parent-link their stage spans to
            ticket.root = TRACER.span("service/ticket", cat="service",
                                      label=ticket.label,
                                      tenant=tenant).begin()
            ticket.trace_id = ticket.root.sid
            ticket._queue_span = TRACER.span(
                "service/queue", cat="service", parent=ticket.trace_id,
                label=ticket.label).begin()
            # exact tier at ADMISSION: a text seen before never reaches a
            # planner thread or the device lane — decided before the
            # ticket enters the intake queue so no worker can race the
            # completion (admission accounting + trace context stay
            # uniform; _finish_cached releases both)
            cached = None if self.result_cache is None else \
                self.result_cache.lookup_text(query)
            if cached is None:
                self._intake.append(ticket)
                self._cv.notify_all()
        FLIGHT.record("admit", label=ticket.label, tenant=tenant,
                      depth=depth, trace_id=ticket.trace_id or None)
        if cached is not None:
            self._finish_cached(ticket, cached)
        return ticket

    def _try_system(self, ticket: Ticket) -> Optional[Ticket]:
        """Serve a system.*-only statement synchronously, out of band.
        Returns the completed ticket, or None when the statement turned
        out not to reference system tables (a literal mentioned the
        prefix — the caller proceeds through normal admission). Genuine
        system-statement failures (bad SQL, a user-table join) complete
        the ticket typed — they must not consume admission accounting."""
        try:
            table = self.session._maybe_system_query(ticket.query,
                                                     ticket.label)
        except Exception as e:
            ticket.stats = ExecStats(mode="system")
            ticket.fail(e)
            return ticket
        if table is None:
            return None
        ticket.stats = ExecStats(mode="system")
        ticket.finish(table, ticket.stats)
        return ticket

    def sql(self, query: str, label: Optional[str] = None,
            tenant: str = "default", deadline_s: Optional[float] = None,
            backend: Optional[str] = None,
            timeout: Optional[float] = None):
        """Synchronous convenience: submit + result."""
        return self.submit(query, label=label, tenant=tenant,
                           deadline_s=deadline_s,
                           backend=backend).result(timeout)

    def explain_analyze(self, query: str, label: Optional[str] = None,
                        backend: Optional[str] = None):
        """Live EXPLAIN ANALYZE against the serving session: runs the
        statement profiled (Session.explain_analyze) on the shared
        session's statement lock — it waits for the device lane's current
        statement like any serial dispatch, profiles OUTSIDE the ticket
        machinery (no admission, no batching: the profile must measure
        the plan, not the queue), and returns the PlanProfile (result on
        ``.table``, bit-identical to a served query). Operator surface:
        diagnostics while the service runs, not a data path."""
        if not self._running:
            raise ServiceClosed("service closed")
        return self.session.explain_analyze(query, backend=backend,
                                            label=label)

    @staticmethod
    def _auto_label(query: str) -> str:
        import hashlib
        return "q" + hashlib.sha1(query.encode()).hexdigest()[:8]

    def _finish_cached(self, ticket: Ticket, hit) -> None:
        """Complete a ticket from the result cache: the result Table is
        shared read-only across every hit (the same contract batched
        parameter-identical tickets already live under)."""
        wait = ticket.mark_started()
        _metrics.SERVICE_QUEUE_WAIT_MS.inc(wait)
        stats = ExecStats(
            mode="cached" if hit.kind == "exact" else "cached_subsumed",
            queue_wait_ms=wait, trace_id=ticket.trace_id or None)
        self._finish_ticket(ticket, result=hit.table, stats=stats)

    # -- planner stage -------------------------------------------------------
    def _plan_worker(self) -> None:
        while True:
            with self._cv:
                while self._running and not self._intake:
                    self._cv.wait(0.1)
                if not self._running:
                    return
                ticket = self._intake.popleft()
            ticket.picked_up()
            if self._expire_if_late(ticket, "queued"):
                continue
            t0 = time.perf_counter()
            try:
                # hop 1 (client thread -> planner worker): parent-linked
                # through the ticket's root span id
                with TRACER.span("service/plan", cat="service",
                                 parent=ticket.trace_id,
                                 label=ticket.label):
                    self._plan_ticket(ticket)
            except Exception as e:
                self._finish_ticket(ticket, error=e)
                continue
            plan_ms = (time.perf_counter() - t0) * 1000.0
            ticket.plan_ms = round(plan_ms, 3)  # lint: lock-exempt (single-owner: the planner worker holds the ticket exclusively until it enqueues to _ready)
            _observe_phase("service_plan_ms", plan_ms, ticket.tenant,
                           ticket.template)
            FLIGHT.record("plan", label=ticket.label, tenant=ticket.tenant,
                          template=ticket.template,
                          ms=round(plan_ms, 3), batchable=bool(ticket.fp))
            if self.result_cache is not None:
                # plan-level tiers: a first-sighting TEXT of an already-
                # cached template (exact by fingerprint + parameters), or
                # a provably-narrower filter answered by re-filtering the
                # cached coarser aggregate — either way the device lane
                # never sees the ticket
                hit = self.result_cache.lookup_plan(
                    ticket.query, ticket.plan, ticket.fp, ticket.pvalues,
                    use_jax=ticket.use_jax)
                if hit is not None:
                    self._finish_cached(ticket, hit)
                    continue
            if self.config.inflight_dedup and ticket.fp is not None \
                    and self._attach_inflight(ticket):
                continue
            ticket.begin_wait()
            with self._cv:
                self._ready.append(ticket)
                self._cv.notify_all()

    def _attach_inflight(self, ticket: Ticket) -> bool:
        """In-flight dedup: park ``ticket`` on an already-admitted
        in-flight leader computing the identical result. The key is the
        full result identity — parameterized-plan fingerprint, parameter
        vector, backend, catalog generation, warehouse snapshot — so a
        registration or commit between the two admissions makes distinct
        keys (never a stale share). Returns True when parked (the ticket
        must not enter the ready queue); the leader's ``_finish_ticket``
        drains followers on every terminal outcome."""
        session = self.session
        key = (ticket.fp, ticket.pvalues,
               "jax" if ticket.use_jax else "numpy",
               session._generation, session._warehouse_version)
        with self._cv:
            leader = self._inflight.get(key)
            if leader is not None and not leader.done():
                leader._dedup_followers.append(ticket)
            else:
                self._inflight[key] = ticket
                ticket._dedup_key = key
                return False
        _metrics.SERVICE_INFLIGHT_DEDUP.inc()
        FLIGHT.record("dedup", label=ticket.label, tenant=ticket.tenant,
                      leader=leader.label, template=ticket.template)
        return True

    def _plan_ticket(self, ticket: Ticket) -> None:
        """Parse/plan/parameterize one query via the cross-client plan
        cache. Runs on planner threads: touches only the session's
        lock-protected read surfaces (catalog schemas, column stats)."""
        from ..sql import parse_sql
        from ..engine.planner import Planner
        from ..engine import streaming
        from ..engine.jax_backend import pallas_kernels as _pk
        from ..engine.jax_backend.executor import shared_fingerprint
        from ..engine.plan import parameterize_plan

        session = self.session
        cfg = session.config
        use_jax = (ticket.backend == "jax") if ticket.backend \
            else cfg.use_jax
        cache_key = session._stream_config_key()
        with self._cv:
            if self._plan_cache_key != cache_key:
                self._plan_cache.clear()
                self._plan_cache_key = cache_key
            entry = self._plan_cache.get(ticket.query)
            if entry is not None:
                self._plan_cache.move_to_end(ticket.query)
        if entry is None:
            # label passed EXPLICITLY: planner threads run outside the
            # session's statement lock, so _active_label belongs to
            # whatever statement the device lane is executing — the
            # adaptive catalog must scope observed-row lookups to THIS
            # ticket's template
            plan = Planner(session._catalog(ticket.label or "")).plan_query(
                parse_sql(ticket.query))
            streams = False
            if use_jax and cfg.out_of_core:
                jobs = streaming.find_streaming_jobs(
                    plan,
                    lambda t: session._est_rows_for(t, 0,
                                                    ticket.label or ""),
                    cfg.out_of_core_min_rows)
                streams = bool(jobs)
            fp = None
            pvalues: tuple = ()
            if use_jax and not streams and cfg.jit_plans \
                    and not cfg.mesh_shape:
                # the batching identity: two texts whose parameterized
                # plans share this fingerprint differ only in hoisted
                # literal VALUES — one compiled program serves both
                pplan, pvals, pdts = parameterize_plan(plan)
                if pdts:
                    fp = shared_fingerprint(
                        pplan, cfg.shard_min_rows,
                        _pk.parse_ops(cfg.pallas_ops))
                    pvalues = tuple(pvals)
            entry = _PlannedQuery(plan, fp, pvalues, streams)
            with self._cv:
                self._plan_cache[ticket.query] = entry
                while len(self._plan_cache) > self.config.plan_cache_entries:
                    self._plan_cache.popitem(last=False)
        ticket.set_planned(entry.plan, None if entry.streams else entry.fp,
                           entry.pvalues, use_jax, streams=entry.streams)

    # -- device lane ---------------------------------------------------------
    def _device_loop(self) -> None:  # lint: device-lane (lane loop: the single device-dispatch thread)
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            if not batch:
                continue
            try:
                self._serve(batch)
            except BaseException as e:  # lane must never die with clients waiting
                for t in batch:
                    if not t.done():
                        self._finish_ticket(t, error=e)
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise

    def _next_batch(self) -> Optional[list]:  # lint: device-lane (runs on the device-lane thread)
        cfg = self.config
        with self._cv:
            while self._running and (self._hold or not self._ready):
                self._cv.wait(0.05)
            if not self._running:
                return None
        if cfg.batch_linger_ms > 0:
            time.sleep(cfg.batch_linger_ms / 1000.0)  # lint: device-lane-exempt (the batch linger IS the lane's own coalescing window — a deliberate, config-bounded wait, not I/O)
        with self._cv:
            out = []
            while self._ready and len(out) < max(1, cfg.max_batch):
                out.append(self._ready.popleft())
            return out

    def _serve(self, batch: list) -> None:  # lint: device-lane (runs on the device-lane thread)
        """Execute one drained window: expire late tickets, coalesce
        compatible parameterized plans into batched dispatches, serve the
        rest serially in arrival order."""
        live = []
        for t in batch:
            if not self._expire_if_late(t, "waiting for the device lane"):
                live.append(t)
        groups: "OrderedDict[str, list]" = OrderedDict()
        serial: list = []
        for t in live:
            if self.config.batching and t.fp is not None and t.use_jax:
                groups.setdefault(t.fp, []).append(t)
            else:
                serial.append(t)
        for fp, members in groups.items():
            if len(members) < 2:
                serial.extend(members)
                continue
            if not self._serve_batched(fp, members):
                serial.extend(members)
        for t in serial:
            self._serve_serial(t)

    def _charge_tenant(self, tenant: str, cost_s: float) -> None:
        """Account one dispatch's device-lane wall to its tenant's
        weighted-fair virtual time (no-op under the FIFO queue)."""
        if not self.config.fair_queue:
            return
        with self._cv:
            self._ready.charge(tenant, cost_s)

    def _preempt_tick(self) -> None:  # lint: device-lane (runs on the device-lane thread)
        """One morsel-boundary yield point (Session._maybe_preempt calls
        here between scan groups / morsels, ON the thread that holds the
        session's statement lock mid-stream): serve up to ``preempt_max``
        non-streamed ready tickets right now, then let the stream resume
        its cached state. Each nested dispatch runs inside
        ``session.preempt_scope()`` — statement-scoped session state is
        saved/restored and the RLock re-entry on this same thread is what
        makes the nested statement legal — and never under the lane
        watchdog (``run_with_deadline`` would move the dispatch to a
        thread that cannot re-enter this thread's RLock)."""
        served = 0
        while served < max(1, self.config.preempt_max):
            with self._cv:
                if not self._running or self._hold:
                    return
                ticket = self._pop_preemptable_locked()
            if ticket is None:
                return
            if self._expire_if_late(ticket, "preempting"):
                continue
            _metrics.SERVICE_PREEMPTIONS.inc()
            FLIGHT.record("preempt", label=ticket.label,
                          tenant=ticket.tenant, template=ticket.template)
            with self.session.preempt_scope():
                self._serve_serial(ticket, preempted=True)
            self._preempt_served += 1
            served += 1

    def _pop_preemptable_locked(self):
        """First non-streamed ready ticket (fair order under the WFQ,
        arrival order under the FIFO deque), or None. Caller holds _cv."""
        ready = self._ready
        if hasattr(ready, "pop_preemptable"):
            return ready.pop_preemptable()
        for ticket in ready:
            if not ticket.streams:
                ready.remove(ticket)
                return ticket
        return None

    def _serve_batched(self, fp: str, members: list) -> bool:  # lint: device-lane (runs on the device-lane thread)
        """One compiled program over the group's stacked parameter vectors;
        parameter-identical members deduplicate to one row. Returns False
        when batching is unavailable/drifted — the caller serves the group
        serially (which also records/compiles the shared program the NEXT
        batch of this template will ride)."""
        from ..engine.jax_backend.device import to_host

        session = self.session
        rows: list[tuple] = []
        index: dict[tuple, int] = {}
        member_rows = []
        for t in members:
            i = index.get(t.pvalues)
            if i is None:
                i = index[t.pvalues] = len(rows)
                rows.append(t.pvalues)
            member_rows.append(i)
        waits = [t.mark_started() for t in members]
        dedup = len(members) - len(rows)
        # hop 2 (planner worker -> device lane): every member gets its own
        # dispatch span covering the shared batched dispatch, parent-linked
        # to ITS ticket root and annotated with the batch composition —
        # one Chrome-trace export shows who co-rode which dispatch
        dspans = [TRACER.span("service/dispatch", cat="service",
                              parent=t.trace_id, label=t.label,
                              batch_leader=members[0].label,
                              batched_with=len(members) - 1,
                              batch_rows=len(rows), dedup=dedup).begin()
                  for t in members]
        cache = self.result_cache
        cache_gens = cache.snapshot_gens(members[0].plan) \
            if cache is not None and members[0].plan is not None else None
        t0 = time.perf_counter()
        with session._sql_lock:
            jexec = session._jax_executor()
            try:
                outs = jexec.run_param_batch(fp, rows)
            except Exception as e:
                # schedule drift (ReplayMismatch), trace failure, transient
                # runtime error: the serial path both surfaces any genuine
                # per-query failure and repairs the shared entry
                outs = None
                batch_error = type(e).__name__
            else:
                batch_error = None if outs is not None else "unavailable"
            if outs is None:
                if batch_error != "unavailable" and self.config.quarantine:
                    # a genuine failure THROUGH the shared program is a
                    # quarantine strike: the same entry failing repeatedly
                    # is evicted (shared + this session's local copy) so
                    # the next sighting re-records fresh instead of every
                    # adopter replaying the poison
                    from ..engine.jax_backend.executor import \
                        strike_shared_program
                    if strike_shared_program(fp, reason=batch_error):
                        jexec.evict_fp(fp)
                for t, sp in zip(members, dspans):
                    sp.end(error=batch_error)
                    t.queue_wait_ms = None   # serial path re-measures
                FLIGHT.record("retry", label=members[0].label,
                              queries=len(members), reason=batch_error,
                              via="serial_fallback")
                return False
            exec_stats = dict(jexec.last_stats)
            if self.config.quarantine:
                from ..engine.jax_backend.executor import \
                    absolve_shared_program
                absolve_shared_program(fp)
        exec_ms = (time.perf_counter() - t0) * 1000.0
        for t, sp in zip(members, dspans):
            sp.end()
            t.exec_ms = round(exec_ms, 3)
            _observe_phase("service_exec_ms", exec_ms, t.tenant, t.template)
            # fair accounting: the batch's wall splits evenly across its
            # members — each tenant pays for the share it rode
            self._charge_tenant(t.tenant, exec_ms / 1000.0 / len(members))
        device_ms = exec_stats.get("device_ms")
        with _metrics.METRICS.locked():
            # one logical event, three counters: the shared value lock
            # keeps any concurrent snapshot from seeing a batch counted
            # without its member queries (consistent bench deltas)
            _metrics.SERVICE_BATCHES.inc()
            _metrics.SERVICE_BATCHED_QUERIES.inc(len(members))
            _metrics.QUERIES_RUN.inc(len(members))
        FLIGHT.record("batch", leader=members[0].label,
                      queries=len(members), rows=len(rows), dedup=dedup,
                      ms=round(exec_ms, 3))
        cells: dict[int, tuple] = {}

        def shared_cell(ri, rep):
            # parameter-identical tickets share ONE materialized Table:
            # the row was computed once, so it converts once too (first
            # result() call wins, the rest reuse) — and conversion happens
            # on client threads, not behind the device lane. The result
            # cache rides the same deferred conversion: the first
            # materialization also stores the entry (with the lane-time
            # generation snapshot, so a racing registration invalidates)
            if ri not in cells:
                cell = {"dt": outs[ri], "table": None,
                        "lock": threading.Lock()}

                def mat(_cell=cell, _rep=rep):
                    with _cell["lock"]:
                        if _cell["table"] is None:
                            _cell["table"] = to_host(_cell["dt"])
                            _cell["dt"] = None
                            if cache is not None and _rep.plan is not None:
                                cache.store(_rep.query, _rep.plan,
                                            _rep.fp, _rep.pvalues,
                                            _cell["table"], use_jax=True,
                                            gens=cache_gens)
                    return _cell["table"]
                cells[ri] = (cell, mat)
            return cells[ri]

        for t, ri, wait in zip(members, member_rows, waits):
            _metrics.SERVICE_QUEUE_WAIT_MS.inc(wait)
            stats = ExecStats(mode="batched", device_ms=device_ms,
                              queue_wait_ms=wait,
                              batched_with=len(members) - 1,
                              trace_id=t.trace_id or None)
            cell, mat = shared_cell(ri, t)
            self._finish_ticket(t, result=cell, stats=stats,
                                materialize=lambda _c, _m=mat: _m(_c))
        with session._sql_lock:
            # the shared observability view mirrors direct sql() behavior:
            # last_exec_stats describes the most recent completed dispatch
            last = ExecStats(mode="batched", device_ms=device_ms,
                             queue_wait_ms=waits[-1],
                             batched_with=len(members) - 1)
            # log=False: every member ticket cuts its own query-log row
            # at _finish_ticket — this shared last-dispatch view must not
            # add an unattributed duplicate
            session._finish_exec_stats(last, log=False)
        return True

    def _serve_serial(self, ticket: Ticket,  # lint: device-lane (runs on the device-lane thread)
                      preempted: bool = False) -> None:
        """The normal Session path (record/adopt/replay, streaming,
        segmentation, host fallback) with the service's pre-built plan —
        result + per-query stats captured atomically. Self-healing rides
        here: a dispatch outliving the lane watchdog is abandoned (fresh
        session locks, the power.py recovery move) and fails typed while
        neighbors proceed; a transient failure inside the retry budget
        requeues off the lane instead of failing the client; repeated
        failures through a shared program strike it toward quarantine.

        preempted=True: this dispatch runs NESTED at another dispatch's
        morsel-boundary yield point (same thread, inside preempt_scope) —
        the lane watchdog is bypassed (its worker thread could not
        re-enter this thread's session RLock) and the preemption counter
        attribution belongs to the OUTER dispatch."""
        ticket.attempts += 1
        wait = ticket.mark_started()
        _metrics.SERVICE_QUEUE_WAIT_MS.inc(wait)
        if not preempted:
            # fresh attribution window: yield points fired during THIS
            # dispatch accumulate here (same-thread single-writer)
            self._preempt_served = 0
        # generation snapshot BEFORE dispatch: a registration racing the
        # execution then stamps the stored entry stale instead of current
        gens = None
        if self.result_cache is not None and ticket.plan is not None:
            gens = self.result_cache.snapshot_gens(ticket.plan)
        t0 = time.perf_counter()
        try:
            # hop 2, serial lane: the session's own "query" span tree
            # nests under this one via the lane thread's span stack, so
            # the ticket root reaches down to parse/plan/morsel spans
            with TRACER.span("service/dispatch", cat="service",
                             parent=ticket.trace_id, label=ticket.label):
                table, stats = self._dispatch_serial(ticket, preempted)
        except Exception as e:
            self._charge_tenant(ticket.tenant, time.perf_counter() - t0)
            if not preempted:
                ticket.preempted = self._preempt_served
            if self.config.quarantine and ticket.fp is not None:
                from ..engine.jax_backend.executor import \
                    strike_shared_program
                if strike_shared_program(ticket.fp,
                                         reason=type(e).__name__):
                    with self.session._sql_lock:
                        self.session._jax_executor().evict_fp(ticket.fp)
            if self._maybe_requeue(ticket, e):
                return
            self._finish_ticket(ticket, error=e)
            return
        if self.config.quarantine and ticket.fp is not None:
            from ..engine.jax_backend.executor import absolve_shared_program
            absolve_shared_program(ticket.fp)
        exec_s = time.perf_counter() - t0
        ticket.exec_ms = round(exec_s * 1000.0, 3)
        _observe_phase("service_exec_ms", ticket.exec_ms,
                       ticket.tenant, ticket.template)
        self._charge_tenant(ticket.tenant, exec_s)
        if not preempted:
            ticket.preempted = self._preempt_served
        if stats is None:
            stats = ExecStats(mode="host")
        stats.queue_wait_ms = wait
        stats.trace_id = ticket.trace_id or None
        if self.result_cache is not None and ticket.plan is not None:
            self.result_cache.store(ticket.query, ticket.plan, ticket.fp,
                                    ticket.pvalues, table,
                                    use_jax=ticket.use_jax, gens=gens)
        self._finish_ticket(ticket, result=table, stats=stats)

    def _dispatch_serial(self, ticket: Ticket, preempted: bool = False):  # lint: device-lane (runs on the device-lane thread)
        """One serial session dispatch, optionally under the device-lane
        watchdog (ServiceConfig.dispatch_timeout_s): on overrun the stuck
        worker is ABANDONED, the session swaps in fresh statement locks
        (power.py's deadline-kill recovery), the trip is flight-dumped,
        and typed DeadlineExceeded propagates — the lane moves on instead
        of wedging every queued neighbor behind one hung dispatch.

        Preempted dispatches NEVER take the watchdog: run_with_deadline
        executes on a worker thread, and the session's statement RLock —
        already held by the paused stream on THIS thread — is not
        reentrant across threads; the nested dispatch must stay here."""
        cfg = self.config

        def run():
            return self.session.service_run(
                ticket.query, backend=ticket.backend,
                label=ticket.label, plan=ticket.plan)

        if preempted or cfg.dispatch_timeout_s <= 0:
            return run()
        try:
            return run_with_deadline(run, cfg.dispatch_timeout_s,
                                     label=f"dispatch:{ticket.label}")
        except DeadlineExceeded:
            self.session.abandon_inflight()
            FLIGHT.trip("lane_watchdog", label=ticket.label,
                        tenant=ticket.tenant,
                        budget_s=cfg.dispatch_timeout_s)
            raise

    def _maybe_requeue(self, ticket: Ticket, error: BaseException) -> bool:
        """Transient-failure re-dispatch off the device lane: requeue the
        ticket at the back of the ready queue (no lane-blocking backoff)
        while the per-ticket attempt cap, the service-lifetime retry
        budget, and the ticket's own deadline all have room. Fatal classes
        (DeadlineExceeded, CircuitOpen — see the resilience classification
        table) never requeue."""
        cfg = self.config
        if cfg.retry_budget <= 0 or ticket.attempts >= cfg.ticket_attempts:
            return False
        if self._retry_policy.classify(error) != "transient":
            return False
        if ticket.deadline.expired():
            return False
        with self._cv:
            if not self._running or self._retry_budget_left <= 0:
                return False
            self._retry_budget_left -= 1
        _metrics.RETRY_BUDGET_SPENT.inc()
        FLIGHT.record("retry", label=ticket.label, tenant=ticket.tenant,
                      error=type(error).__name__, attempt=ticket.attempts,
                      via="requeue")
        ticket.queue_wait_ms = None   # the retried dispatch re-measures
        ticket.begin_wait()
        with self._cv:
            self._ready.append(ticket)
            self._cv.notify_all()
        return True

    # -- shared bookkeeping --------------------------------------------------
    def _expire_if_late(self, ticket: Ticket, where: str) -> bool:
        if not ticket.deadline.expired():
            return False
        _metrics.SERVICE_DEADLINE_EXPIRED.inc()
        FLIGHT.record("expire", label=ticket.label, tenant=ticket.tenant,
                      where=where, budget_s=ticket.deadline.seconds)
        self._finish_ticket(ticket, error=DeadlineExceeded(
            f"query {ticket.label!r} ({ticket.tenant}) exceeded its "
            f"{ticket.deadline.seconds}s budget while {where}"))
        return True

    def _finish_ticket(self, ticket: Ticket, result=None,
                       stats: Optional[ExecStats] = None,
                       error: Optional[BaseException] = None,
                       materialize=None) -> None:
        followers = None
        if ticket._dedup_key is not None:
            # release the in-flight leadership and take the follower list
            # atomically: a racing _attach_inflight either saw the leader
            # undone (parked here, drained below) or finds the registry
            # slot free and becomes the next leader
            with self._cv:
                self._inflight.pop(ticket._dedup_key, None)
                ticket._dedup_key = None
                followers = ticket._dedup_followers
                ticket._dedup_followers = []
        err_name = type(error).__name__ if error is not None else None
        ticket.close_stage_spans(error=err_name)
        latency_ms = round(
            (time.perf_counter() - ticket.submitted_at) * 1000.0, 3)
        from ..obs.query_log import QUERY_LOG
        if QUERY_LOG.enabled:
            # the ticket's durable query-log row: the service path logs
            # with full context (tenant/template/phase walls/error class)
            # — the session's own append is suppressed for service
            # statements, so this is the one row per ticket
            QUERY_LOG.record(
                stats, source="service", label=ticket.label,
                tenant=ticket.tenant, template=ticket.template,
                trace_id=ticket.trace_id or None, wall_ms=latency_ms,
                queue_ms=ticket.queue_wait_ms, plan_ms=ticket.plan_ms,
                exec_ms=ticket.exec_ms, status=err_name,
                error=error, preempted=ticket.preempted,
                rows=getattr(result, "num_rows", None))
        if error is not None:
            ticket.fail(error)
            FLIGHT.record("error", label=ticket.label,
                          tenant=ticket.tenant, error=err_name,
                          latency_ms=latency_ms)
        else:
            ticket.finish(result, stats, materialize=materialize)
            # the SLO distribution: admission -> completion (deferred
            # client-side materialization is measured separately)
            _observe_phase("service_latency_ms", latency_ms,
                           ticket.tenant, ticket.template)
            FLIGHT.record("complete", label=ticket.label,
                          tenant=ticket.tenant, template=ticket.template,
                          latency_ms=latency_ms,
                          queue_wait_ms=ticket.queue_wait_ms,
                          batched_with=stats.batched_with
                          if stats else None,
                          trace_id=ticket.trace_id or None)
        if ticket.root is not None:
            ticket.root.set(latency_ms=latency_ms)
            ticket.root.end(error=err_name)
            ticket.root = None
        if self._breaker is not None:
            # every terminal outcome teaches the breaker (probe slots are
            # released here too); requeued tickets report only their
            # final disposition
            self._breaker.record(err_name, probe=ticket._probe,
                                 label=ticket.label)
            ticket._probe = None
        with self._cv:
            self._pending -= 1
            _metrics.SERVICE_QUEUE_DEPTH.set(self._pending)
            self._cv.notify_all()
        if followers:
            # drain the parked followers on the leader's terminal
            # outcome: shared result cell (the batched-ticket contract —
            # read-only Table, one deferred materialization) or the same
            # typed error; a follower whose own deadline lapsed while
            # parked fails on ITS budget, not the leader's result
            for f in followers:
                if self._expire_if_late(f, "deduped on an in-flight "
                                           "leader"):
                    continue
                if error is not None:
                    self._finish_ticket(f, error=error)
                else:
                    fwait = f.mark_started()
                    _metrics.SERVICE_QUEUE_WAIT_MS.inc(fwait)
                    fstats = ExecStats(mode="deduped", queue_wait_ms=fwait,
                                       trace_id=f.trace_id or None)
                    self._finish_ticket(f, result=result, stats=fstats,
                                        materialize=materialize)
