"""Arrow-IPC front door: cross-process serving for the query service.

PR 10's :class:`~nds_tpu.service.QueryService` is in-process — "N
clients" meant N threads importing the engine. This module is the wire
layer that turns one engine process into a server: N client PROCESSES
submit SQL + tenant + deadline over a stdlib socket, results return as
Arrow IPC, and every admission/breaker/deadline/batching/fair-scheduling
decision stays in ``service.py`` unchanged (the front door calls
``service.submit`` like any in-process client would).

Frame layout (both directions, one frame per message)::

    u32 big-endian  header length H
    H bytes         header, UTF-8 JSON (op / status / stats / error)
    u64 big-endian  body length B
    B bytes         body (Arrow IPC stream bytes; empty when B = 0)

Request ops:

- ``query``: ``{op, sql, tenant, label, deadline_s, backend, hash}`` —
  the USER query path. The handler thread submits, blocks on the
  ticket, materializes, and serializes — all OFF the device lane, which
  only ever sees the dispatch itself. Response body = result as one
  Arrow IPC stream; header carries the per-query stats and (``hash:
  true`` requests) a canonical engine-result hash for bit-identity
  audits.
- ``ping``: liveness + the server's cache EPOCH (fresh per server
  start, so a restarted engine invalidates every client-held entry).
- ``cache_snapshot``: the result cache's exact tier as Arrow IPC — the
  header lists (sql, backend, gens, snaps) per entry, the body is the
  concatenation of ``u64 len | IPC stream`` blobs in header order.
  N fresh front-end processes warm from one snapshot instead of N cold
  sets.
- ``cache_validate``: the invalidation handshake — the client sends the
  stamps (per-table catalog generations + warehouse snapshot versions)
  and epoch of entries it wants to trust, the server answers one bool
  each against the LIVE session. A commit or re-registration between
  snapshot and use answers False; an epoch mismatch answers all False.
- ``chaos``: arm fault specs in the SERVER process (the topology
  campaign's remote trigger). Refused unless the server was started
  with ``allow_chaos=True`` — never on by default.

Errors cross the wire TYPED: the response header carries the resilience
class name + its constructor fields, and the client reconstructs the
real exception (:class:`AdmissionRejected` with depth/limit,
:class:`CircuitOpen` with error_class/retry_after_s, ...) so every
existing backoff/retry policy works unchanged against remote failures.
Unknown classes land as :class:`RemoteQueryError` — still typed, never
a bare string.

Fault points (chaos topology campaign): ``frontdoor.drop`` severs the
connection instead of writing a response (client sees EOF mid-frame and
raises :class:`ConnectionDropped`, a TransientError — its retry loop
re-submits); ``frontdoor.kill`` hard-exits the engine process before a
query dispatches (the mid-query kill).
"""
from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
import time
import uuid
from typing import Optional

from ..obs import metrics as _metrics
from ..obs.flight import FLIGHT
from ..resilience import (FAULTS, AdmissionRejected, CircuitOpen,
                          DeadlineExceeded, FaultError, TransientError)
from .service import ServiceClosed

#: request header / body hard bounds: a malformed or hostile length
#: prefix fails typed instead of ballooning server memory
MAX_HEADER_BYTES = 1 << 20
MAX_BODY_BYTES = 1 << 28
#: default client-side wall for one blocking request
DEFAULT_TIMEOUT_S = 300.0


class ConnectionDropped(TransientError):
    """The front-door connection died mid-request (EOF, reset, refused):
    transient by classification — the client retry loop reconnects and
    re-submits, the wire-level analogue of the service requeue."""


class RemoteQueryError(RuntimeError):
    """A server-side error class the client has no local type for —
    still typed (``cls`` carries the remote class name)."""

    def __init__(self, message: str, cls: str = ""):
        super().__init__(message)
        self.cls = cls


# -- frame + payload codecs ----------------------------------------------------

def write_frame(wfile, header: dict, body: bytes = b"") -> None:
    h = json.dumps(header, separators=(",", ":")).encode()
    wfile.write(struct.pack(">I", len(h)) + h
                + struct.pack(">Q", len(body)) + body)
    wfile.flush()


def _read_exact(rfile, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = rfile.read(n - len(out))
        if not chunk:
            raise ConnectionDropped(
                f"connection closed mid-frame ({len(out)}/{n} bytes)")
        out += chunk
    return out


def read_frame(rfile) -> tuple[dict, bytes]:
    """One frame, or raises ConnectionDropped (EOF/short read) /
    ValueError (bound exceeded, malformed JSON)."""
    hlen = struct.unpack(">I", _read_exact(rfile, 4))[0]
    if hlen > MAX_HEADER_BYTES:
        raise ValueError(f"frame header {hlen} bytes exceeds "  # lint: typed-error-exempt (framing-bound violation is deliberately NOT retryable: a typed TransientError would make clients re-send the same oversized frame; the connection is torn down instead)
                         f"bound {MAX_HEADER_BYTES}")
    header = json.loads(_read_exact(rfile, hlen).decode())
    blen = struct.unpack(">Q", _read_exact(rfile, 8))[0]
    if blen > MAX_BODY_BYTES:
        raise ValueError(f"frame body {blen} bytes exceeds "  # lint: typed-error-exempt (same deliberate non-retryable framing bound as the header check above)
                         f"bound {MAX_BODY_BYTES}")
    return header, _read_exact(rfile, blen) if blen else b""


def table_to_ipc(table) -> bytes:
    """One pa.Table -> Arrow IPC stream bytes."""
    import pyarrow as pa
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def ipc_to_table(data: bytes):
    """Arrow IPC stream bytes -> pa.Table."""
    import pyarrow as pa
    return pa.ipc.open_stream(pa.BufferReader(data)).read_all()


def result_hash(table) -> str:
    """Canonical engine-result digest (chaos.result_hash's recipe): the
    server stamps responses with it so clients/benches can assert
    bit-identity against a serial execution without shipping both."""
    import hashlib
    return hashlib.sha1(repr(table.to_pylist()).encode()).hexdigest()


def _error_doc(e: BaseException) -> dict:
    """Typed error -> wire dict: class name + the resilience hierarchy's
    constructor fields (absent fields are simply not sent)."""
    fields = {}
    for k in ("depth", "limit", "error_class", "retry_after_s"):
        v = getattr(e, k, None)
        if v is not None:
            fields[k] = v
    return {"cls": type(e).__name__, "msg": str(e), "fields": fields}


def reconstruct_error(doc: dict) -> BaseException:
    """Wire dict -> the real typed exception, so client-side retry
    policies classify remote failures exactly like local ones."""
    cls = doc.get("cls", "RemoteQueryError")
    msg = doc.get("msg", "")
    f = doc.get("fields") or {}
    if cls == "ServiceClosed":
        return ServiceClosed(msg, depth=f.get("depth"),
                             limit=f.get("limit"))
    if cls == "CircuitOpen":
        return CircuitOpen(msg, error_class=f.get("error_class"),
                           retry_after_s=f.get("retry_after_s"))
    if cls == "AdmissionRejected":
        return AdmissionRejected(msg, depth=f.get("depth"),
                                 limit=f.get("limit"))
    if cls == "DeadlineExceeded":
        return DeadlineExceeded(msg)
    if cls == "FaultError":
        return FaultError(msg)
    if cls == "ConnectionDropped":
        return ConnectionDropped(msg)
    if cls == "TransientError":
        return TransientError(msg)
    if cls == "TimeoutError":
        return TimeoutError(msg)
    if cls == "PermissionError":
        return PermissionError(msg)
    return RemoteQueryError(f"{cls}: {msg}", cls=cls)


# -- server --------------------------------------------------------------------

class _FrontDoorTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    frontdoor: "FrontDoorServer"


class _Handler(socketserver.StreamRequestHandler):
    """One connected client process: frames served in a loop until EOF
    (connections are persistent — a dashboard client submits thousands
    of queries over one socket). Everything here runs on the acceptor's
    per-connection thread: admission, blocking on the ticket, deferred
    materialization, Arrow serialization — the device lane never waits
    on this socket."""

    def handle(self) -> None:
        fd = self.server.frontdoor
        while True:
            try:
                header, body = read_frame(self.rfile)
            except ConnectionDropped:
                return                      # client went away: normal
            except Exception as e:
                # malformed frame: answer typed once, then drop the
                # connection (framing is lost — resync is impossible)
                self._reply_error(ValueError(f"malformed frame: {e}"))
                return
            _metrics.FRONTDOOR_REQUESTS.inc()
            try:
                if not self._serve_one(fd, header, body):
                    return
            except ConnectionDropped:
                return                      # injected drop severed us
            except BrokenPipeError:
                return
            except Exception as e:
                if not self._reply_error(e):
                    return

    def _serve_one(self, fd: "FrontDoorServer", header: dict,
                   body: bytes) -> bool:
        """Dispatch one request frame; False ends the connection."""
        op = header.get("op")
        if op == "query":
            return self._op_query(fd, header)
        if op == "ping":
            return self._reply({"ok": True, "epoch": fd.epoch,
                                "pid": os.getpid()})
        if op == "cache_snapshot":
            return self._op_cache_snapshot(fd)
        if op == "cache_validate":
            return self._op_cache_validate(fd, header)
        if op == "chaos":
            return self._op_chaos(fd, header)
        return self._reply_error(ValueError(f"unknown op {op!r}"))

    def _op_query(self, fd: "FrontDoorServer", header: dict) -> bool:
        from ..engine import arrow_bridge

        sql = header.get("sql")
        if not isinstance(sql, str) or not sql.strip():
            return self._reply_error(ValueError("query op without sql"))
        label = header.get("label") or None
        # the mid-query kill window: the request is admitted to the
        # server's log/flight but its result will never be produced
        try:
            FAULTS.fire("frontdoor.kill", label or sql[:40])
        except FaultError:
            FLIGHT.trip("frontdoor_kill", label=label)
            os._exit(86)
        ticket = fd.service.submit(
            sql, label=label, tenant=header.get("tenant", "default"),
            deadline_s=header.get("deadline_s"),
            backend=header.get("backend"))
        table = ticket.result(timeout=fd.request_timeout_s)
        resp = {"ok": True,
                "stats": {
                    "mode": ticket.stats.mode if ticket.stats else None,
                    "queue_wait_ms": ticket.queue_wait_ms,
                    "plan_ms": ticket.plan_ms,
                    "exec_ms": ticket.exec_ms,
                    "preempted": ticket.preempted,
                    "template": ticket.template,
                }}
        if header.get("hash"):
            resp["result_hash"] = result_hash(table)
        return self._reply(resp, table_to_ipc(arrow_bridge.to_arrow(table)))

    def _op_cache_snapshot(self, fd: "FrontDoorServer") -> bool:
        from ..engine import arrow_bridge

        cache = fd.service.result_cache
        if cache is None:
            return self._reply({"ok": True, "epoch": fd.epoch,
                                "entries": []})
        items = cache.export_snapshot()
        entries, blobs = [], []
        for it in items:
            blob = table_to_ipc(arrow_bridge.to_arrow(it["result"]))
            blobs.append(struct.pack(">Q", len(blob)) + blob)
            entries.append({"sql": it["sql"], "backend": it["backend"],
                            "gens": it["gens"], "snaps": it["snaps"]})
        _metrics.RESULT_CACHE_SNAPSHOTS.inc()
        FLIGHT.record("cache_snapshot", entries=len(entries))
        return self._reply({"ok": True, "epoch": fd.epoch,
                            "entries": entries}, b"".join(blobs))

    def _op_cache_validate(self, fd: "FrontDoorServer",
                           header: dict) -> bool:
        cache = fd.service.result_cache
        entries = header.get("entries") or []
        if header.get("epoch") != fd.epoch or cache is None:
            # a restarted server (fresh epoch) or a cache-less one can
            # vouch for nothing: every client-held entry is stale
            return self._reply({"ok": True,
                                "valid": [False] * len(entries)})
        valid = [bool(cache.validate_stamps(e.get("gens") or {},
                                            e.get("snaps") or {}))
                 for e in entries]
        return self._reply({"ok": True, "valid": valid})

    def _op_chaos(self, fd: "FrontDoorServer", header: dict) -> bool:
        if not fd.allow_chaos:
            return self._reply_error(PermissionError(
                "chaos op refused: server started without allow_chaos"))
        specs = header.get("specs") or []
        # fired counts of the batch being REPLACED: a disarm ([]) hands
        # the campaign its evidence that the faults actually fired
        fired = [{"point": s.point, "action": s.action, "fired": s.fired}
                 for s in FAULTS.specs() if s.source == "config"]
        FAULTS.configure([str(s) for s in specs])
        return self._reply({"ok": True, "armed": len(specs),
                            "fired": fired})

    # -- response writers ------------------------------------------------------
    def _maybe_drop(self) -> None:
        """The connection-drop fault point: armed, the handler severs
        the socket INSTEAD of writing the response — the client observes
        an abrupt EOF exactly where a real network failure would put
        one."""
        try:
            FAULTS.fire("frontdoor.drop")
        except FaultError:
            _metrics.FRONTDOOR_ERRORS.inc()
            try:
                self.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self.connection.close()
            raise ConnectionDropped("injected frontdoor.drop")

    def _reply(self, header: dict, body: bytes = b"") -> bool:
        self._maybe_drop()
        write_frame(self.wfile, header, body)
        return True

    def _reply_error(self, e: BaseException) -> bool:
        _metrics.FRONTDOOR_ERRORS.inc()
        FLIGHT.record("frontdoor_error", error=type(e).__name__)
        try:
            self._maybe_drop()
            write_frame(self.wfile, {"ok": False, "error": _error_doc(e)})
            return True
        except (ConnectionDropped, BrokenPipeError, OSError):
            return False


class FrontDoorServer:
    """The engine process's wire front door over one QueryService.

    Usage (one engine process)::

        svc = QueryService(session, cfg).start()
        door = FrontDoorServer(svc, port=0).start()
        print(door.port)          # ephemeral bind reads back
        ...
        door.stop()

    ``epoch`` is fresh per instance: client caches warmed from a
    previous server life validate False wholesale after a restart —
    the zero-stale-results guarantee does not depend on clients
    noticing the process died."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0,
                 allow_chaos: bool = False,
                 request_timeout_s: float = DEFAULT_TIMEOUT_S):
        self.service = service
        self.host = host
        self._port = port
        self.allow_chaos = allow_chaos
        self.request_timeout_s = request_timeout_s
        self.epoch = uuid.uuid4().hex
        self._server: Optional[_FrontDoorTCPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1] if self._server \
            else self._port

    def start(self) -> "FrontDoorServer":
        if self._server is not None:
            return self
        self._server = _FrontDoorTCPServer((self.host, self._port),
                                           _Handler)
        self._server.frontdoor = self
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="frontdoor-acceptor",
                                        daemon=True)
        self._thread.start()
        FLIGHT.record("frontdoor_start", host=self.host, port=self.port)
        return self

    def stop(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        self._server = None
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self) -> "FrontDoorServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- client --------------------------------------------------------------------

class FlightClient:
    """Thin synchronous client for the front door (one socket, one
    in-flight request — N concurrency comes from N clients, matching
    the service's one-ticket-per-submit shape).

    ``use_cache=True`` arms the client-side result cache: warm it from
    the server's exact tier with :meth:`warm_cache`, and every ``sql``
    first revalidates a local entry over the ``cache_validate``
    handshake — a hit answers from local memory without touching the
    admission queue; a commit/re-registration/restart on the server
    invalidates the entry on its next use. NOT thread-safe (use one
    client per thread, like one cursor per thread)."""

    def __init__(self, host: str, port: int,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 retries: int = 2, retry_backoff_s: float = 0.05,
                 use_cache: bool = False):
        self.host = host
        self.port = int(port)
        self.timeout_s = timeout_s
        self.retries = max(0, retries)
        self.retry_backoff_s = retry_backoff_s
        self.use_cache = use_cache
        self._sock: Optional[socket.socket] = None
        self._file = None
        #: (sql, backend_tag) -> {table, gens, snaps, epoch}
        self._cache: dict = {}

    # -- connection -------------------------------------------------------------
    def _connect(self):
        if self._file is None:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout_s)
            except OSError as e:
                raise ConnectionDropped(
                    f"connect {self.host}:{self.port} failed: {e}")
            self._file = self._sock.makefile("rwb")
        return self._file

    def close(self) -> None:
        for obj in (self._file, self._sock):
            if obj is not None:
                try:
                    obj.close()
                except OSError:
                    pass
        self._file = self._sock = None

    def __enter__(self) -> "FlightClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _rpc(self, header: dict, body: bytes = b"") -> tuple[dict, bytes]:
        """One request/response exchange; raises the reconstructed typed
        error on an error frame, ConnectionDropped on wire death."""
        f = self._connect()
        try:
            write_frame(f, header, body)
            resp, rbody = read_frame(f)
        except (ConnectionDropped, OSError) as e:
            self.close()
            if isinstance(e, ConnectionDropped):
                raise
            raise ConnectionDropped(f"wire failure: {e}")
        if not resp.get("ok", True) and "error" in resp:
            raise reconstruct_error(resp["error"])
        return resp, rbody

    # -- ops ----------------------------------------------------------------
    def ping(self) -> dict:
        return self._rpc({"op": "ping"})[0]

    def chaos(self, specs: list) -> dict:
        """Arm FaultRegistry specs inside the ENGINE process (replacing
        whatever was armed; ``[]`` disarms). Refused (PermissionError)
        unless the server started with ``allow_chaos`` — the topology
        campaign's remote fault-injection control channel."""
        return self._rpc({"op": "chaos", "specs": list(specs)})[0]

    def warm_cache(self) -> int:
        """Pull the server's exact-tier snapshot into the local cache;
        returns entries loaded. Requires ``use_cache=True``."""
        resp, body = self._rpc({"op": "cache_snapshot"})
        epoch = resp.get("epoch")
        off = 0
        n = 0
        for meta in resp.get("entries", []):
            (blen,) = struct.unpack_from(">Q", body, off)
            off += 8
            table = ipc_to_table(body[off:off + blen])
            off += blen
            self._cache[(meta["sql"], meta.get("backend", "jax"))] = {
                "table": table, "gens": meta.get("gens") or {},
                "snaps": meta.get("snaps") or {}, "epoch": epoch}
            n += 1
        return n

    def _cache_lookup(self, sql: str, backend: Optional[str]):
        """Snapshot-warmed lookup with the per-use validation handshake;
        a False (or failed) validation evicts and misses."""
        key = (sql, backend or "jax")
        entry = self._cache.get(key)
        if entry is None:
            return None
        resp, _ = self._rpc({"op": "cache_validate",
                             "epoch": entry["epoch"],
                             "entries": [{"gens": entry["gens"],
                                          "snaps": entry["snaps"]}]})
        if (resp.get("valid") or [False])[0]:
            _metrics.FRONTDOOR_CLIENT_CACHE_HITS.inc()
            return entry["table"]
        del self._cache[key]
        return None

    def query(self, sql: str, tenant: str = "default",
              label: Optional[str] = None,
              deadline_s: Optional[float] = None,
              backend: Optional[str] = None,
              want_hash: bool = False) -> tuple:
        """Submit one query; returns (pa.Table, response header).

        ConnectionDropped retries RECONNECT + RE-SUBMIT up to
        ``retries`` times (reads are idempotent — the wire analogue of
        the service's requeue); typed server errors raise as their real
        resilience classes."""
        attempt = 0
        while True:
            try:
                if self.use_cache:
                    hit = self._cache_lookup(sql, backend)
                    if hit is not None:
                        return hit, {"ok": True, "cache": "client"}
                header = {"op": "query", "sql": sql, "tenant": tenant}
                if label:
                    header["label"] = label
                if deadline_s is not None:
                    header["deadline_s"] = deadline_s
                if backend:
                    header["backend"] = backend
                if want_hash:
                    header["hash"] = True
                resp, body = self._rpc(header)
                return ipc_to_table(body), resp
            except ConnectionDropped:
                attempt += 1
                if attempt > self.retries:
                    raise
                time.sleep(self.retry_backoff_s * attempt)

    def sql(self, sql: str, **kw):
        """Submit one query; returns its pa.Table."""
        return self.query(sql, **kw)[0]
