"""Load Test: transcode raw pipe-delimited data into the Parquet warehouse.

Capability parity with the reference transcoder (reference
nds/nds_transcode.py): per-table timed load->store loop (transcode
:184-202), explicit-schema CSV reads with '|' delimiter (load :56-65),
partitioned writes for the 7 fact tables and single-file writes for small
dimensions (store :68-151, TABLE_PARTITIONING :45-53), --update mode for
the maintenance staging tables, and a report file carrying the Load Test
Time, per-table times, and the ``RNGSEED used: <MMDDhhmmss f>``
end-timestamp the stream generator seeds from (:204-228).
"""
from __future__ import annotations

import argparse
import os
import sys
import time
from datetime import datetime

import pyarrow as pa
import pyarrow.csv as pa_csv

from .schema import get_maintenance_schemas, get_schemas
from .warehouse import Warehouse

# identical role to reference nds_transcode.py "derived" handling: the
# delete-date tables are inputs to maintenance, not warehouse tables
NON_WAREHOUSE = {"delete", "inventory_delete", "dbgen_version"}


def load_csv(path: str, schema: pa.Schema) -> pa.Table:
    files = ([os.path.join(path, f) for f in sorted(os.listdir(path))]
             if os.path.isdir(path) else [path])
    convert = pa_csv.ConvertOptions(
        column_types={f.name: f.type for f in schema},
        null_values=[""], strings_can_be_null=True)
    read = pa_csv.ReadOptions(column_names=[f.name for f in schema])
    parse = pa_csv.ParseOptions(delimiter="|")
    parts = [pa_csv.read_csv(f, read_options=read, parse_options=parse,
                             convert_options=convert)
             for f in files if os.path.getsize(f) > 0]
    return pa.concat_tables(parts)


def transcode(input_prefix: str, output_prefix: str,
              report_file: str | None = None,
              update: bool = False,
              use_decimal: bool = False,
              tables: list[str] | None = None,
              partition: bool = True) -> dict[str, float]:
    """Transcode every table; returns per-table seconds."""
    schemas = dict(get_maintenance_schemas(use_decimal) if update
                   else get_schemas(use_decimal))
    if tables:
        schemas = {t: schemas[t] for t in tables}
    wh = Warehouse(output_prefix)
    times: dict[str, float] = {}
    for name, sch in schemas.items():
        src = os.path.join(input_prefix, name)
        if not os.path.exists(src):
            continue
        t0 = time.perf_counter()
        table = load_csv(src, sch.arrow_schema(use_decimal=use_decimal))
        if name in NON_WAREHOUSE:
            wh.table(name).create(table, partition=False)
        else:
            wh.table(name).create(table, partition=partition)
        times[name] = time.perf_counter() - t0
        print(f"Time taken: {times[name]:.3f} s for table {name}",
              flush=True)

    total = sum(times.values())
    end = datetime.now()
    # reference RNGSEED format: strftime("%m%d%H%M%S%f")[:-5]
    rngseed = end.strftime("%m%d%H%M%S%f")[:-5]
    lines = [f"Load Test Time: {total:.3f} seconds"]
    lines += [f"Time taken: {t:.3f} s for table {n}"
              for n, t in times.items()]
    lines.append(f"RNGSEED used: {rngseed}")
    report = "\n".join(lines)
    print(report)
    if report_file:
        os.makedirs(os.path.dirname(report_file) or ".", exist_ok=True)
        with open(report_file, "w") as f:
            f.write(report + "\n")
    return times


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="nds_tpu.transcode")
    p.add_argument("input_prefix")
    p.add_argument("output_prefix")
    p.add_argument("report_file", nargs="?", default=None)
    p.add_argument("--update", action="store_true",
                   help="transcode the maintenance staging tables instead")
    p.add_argument("--use_decimal", action="store_true")
    p.add_argument("--tables", default=None,
                   help="comma-separated subset")
    p.add_argument("--no_partition", action="store_true")
    a = p.parse_args(argv)
    transcode(a.input_prefix, a.output_prefix, a.report_file, a.update,
              a.use_decimal,
              a.tables.split(",") if a.tables else None,
              partition=not a.no_partition)
    return 0


if __name__ == "__main__":
    sys.exit(main())
