"""Query-stream generation: the dsqgen-equivalent tool layer.

Capability parity with the reference stream front-end (reference
nds/nds_gen_query_stream.py): instantiate the 99 query templates into N
permuted streams seeded by -rngseed (generate_query_streams :42-89), write
``query_{i}.sql`` files whose queries carry ``-- start query N using
template queryX.tpl`` markers (the power runner splits on these,
nds_power.py:49-76), and split the four two-statement templates
(14, 23, 24, 39) into _part1/_part2 units (split_special_query :91-103).

Template parameterization is original: each .tpl starts with ``-- define
[NAME] = <expr>`` lines (uniform_int, choice, year, etc.) evaluated with a
counter-based RNG keyed by (rngseed, template, param, stream), so any
stream can be generated independently and reproducibly.
"""
from __future__ import annotations

import argparse
import hashlib
import os
import re
import struct
import sys
from typing import Callable

TEMPLATE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "templates")

# the four templates whose body holds two independent statements
# (reference nds_gen_query_stream.py:91-103)
SPECIAL_TEMPLATES = (14, 23, 24, 39)

_DEFINE_RE = re.compile(r"^--\s*define\s+\[(\w+)\]\s*=\s*(.+?)\s*$")


def _rng(rngseed: int, template: int, param: str, stream: int) -> int:
    h = hashlib.sha256(
        f"{rngseed}/{template}/{param}/{stream}".encode()).digest()
    return struct.unpack("<Q", h[:8])[0]


def _eval_param(expr: str, r: int):
    """Evaluate a parameter expression with randomness r.

    Supported forms:
      uniform_int(lo, hi)        inclusive integer
      choice('a', 'b', ...)      uniform pick
      choice_n(k, 'a', ...)      k distinct picks, comma-joined as quoted list
      dist_month()               1..12
    """
    m = re.match(r"^uniform_int\((-?\d+),\s*(-?\d+)\)$", expr)
    if m:
        lo, hi = int(m.group(1)), int(m.group(2))
        return str(lo + r % (hi - lo + 1))
    m = re.match(r"^choice\((.+)\)$", expr)
    if m:
        opts = _split_args(m.group(1))
        return _unquote(opts[r % len(opts)])
    m = re.match(r"^choice_n\((\d+),\s*(.+)\)$", expr)
    if m:
        k = int(m.group(1))
        opts = _split_args(m.group(2))
        picked = []
        rr = r
        pool = list(opts)
        for _ in range(min(k, len(pool))):
            picked.append(pool.pop(rr % len(pool)))
            rr = (rr * 6364136223846793005 + 1442695040888963407) % (1 << 64)
        return ", ".join(picked)
    m = re.match(r"^dist_month\(\)$", expr)
    if m:
        return str(1 + r % 12)
    m = re.match(r"^ziplist\((\d+)\)$", expr)
    if m:
        # k distinct 5-digit zips, quoted + comma-joined (q8-style IN list).
        # Uniform over 0..99999 deliberately: the native generator draws
        # *_zip as `r % 100000` over a mixed hash (native/datagen/gen.cpp,
        # `ends_with(n, "_zip")` branch), so uniform sampling here matches
        # the data's actual zip distribution (dsqgen samples from dsdgen's
        # skewed distribution for the same reason).
        k = int(m.group(1))
        rr, seen = r, []
        while len(seen) < k:
            z = f"'{rr % 100000:05d}'"
            if z not in seen:
                seen.append(z)
            rr = (rr * 6364136223846793005 + 1442695040888963407) % (1 << 64)
        return ", ".join(seen)
    m = re.match(r"^rand_date\((\d+),\s*(\d+)\)$", expr)
    if m:
        # uniform date within [y_lo, y_hi], day 1..28 (dsqgen date params)
        y_lo, y_hi = int(m.group(1)), int(m.group(2))
        y = y_lo + r % (y_hi - y_lo + 1)
        mo = 1 + (r >> 8) % 12
        d = 1 + (r >> 16) % 28
        return f"{y:04d}-{mo:02d}-{d:02d}"
    raise ValueError(f"unsupported parameter expression: {expr!r}")


def _split_args(s: str) -> list[str]:
    out, depth, cur, in_q = [], 0, "", False
    for ch in s:
        if ch == "'" and depth == 0:
            in_q = not in_q
            cur += ch
        elif ch == "," and depth == 0 and not in_q:
            out.append(cur.strip())
            cur = ""
        else:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            cur += ch
    if cur.strip():
        out.append(cur.strip())
    return out


def _unquote(s: str) -> str:
    return s[1:-1] if len(s) >= 2 and s[0] == "'" and s[-1] == "'" else s


def load_template(number: int, template_dir: str = TEMPLATE_DIR
                  ) -> tuple[dict[str, str], str]:
    """Read queryN.tpl -> (param defs, body)."""
    path = os.path.join(template_dir, f"query{number}.tpl")
    defs: dict[str, str] = {}
    body_lines: list[str] = []
    with open(path) as f:
        for line in f:
            m = _DEFINE_RE.match(line.strip())
            if m:
                defs[m.group(1)] = m.group(2)
            else:
                body_lines.append(line.rstrip("\n"))
    return defs, "\n".join(body_lines).strip()


def instantiate(number: int, stream: int, rngseed: int,
                template_dir: str = TEMPLATE_DIR) -> str:
    defs, body = load_template(number, template_dir)
    for name, expr in defs.items():
        value = _eval_param(expr, _rng(rngseed, number, name, stream))
        body = body.replace(f"[{name}]", str(value))
    leftover = re.search(r"\[([A-Z_]+)\]", body)
    if leftover:
        raise ValueError(
            f"query{number}.tpl: unbound parameter [{leftover.group(1)}]")
    return body


def available_templates(template_dir: str = TEMPLATE_DIR) -> list[int]:
    out = []
    for f in os.listdir(template_dir):
        m = re.match(r"^query(\d+)\.tpl$", f)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def _permutation(numbers: list[int], stream: int, rngseed: int) -> list[int]:
    """Deterministic per-stream ordering; stream 0 runs in template order
    (the reference gets permutations from dsqgen's internal tables)."""
    if stream == 0:
        return list(numbers)
    order = list(numbers)
    r = _rng(rngseed, 0, "permutation", stream)
    for i in range(len(order) - 1, 0, -1):
        r = (r * 6364136223846793005 + 1442695040888963407) % (1 << 64)
        j = r % (i + 1)
        order[i], order[j] = order[j], order[i]
    return order


def generate_query_streams(output_dir: str, streams: int, rngseed: int,
                           template_dir: str = TEMPLATE_DIR,
                           template: int | None = None) -> list[str]:
    """Write query_0.sql .. query_{streams-1}.sql (or a single template's
    instantiations when ``template`` is given, mirroring dsqgen -template)."""
    os.makedirs(output_dir, exist_ok=True)
    numbers = [template] if template else available_templates(template_dir)
    paths = []
    for s in range(streams):
        path = os.path.join(output_dir, f"query_{s}.sql")
        with open(path, "w") as f:
            for n in _permutation(numbers, s, rngseed):
                sql = instantiate(n, s, rngseed, template_dir)
                f.write(f"-- start query {n} using template query{n}.tpl\n")
                f.write(sql.rstrip().rstrip(";") + ";\n\n")
        paths.append(path)
    return paths


def split_special_query(query_name: str, sql: str) -> list[tuple[str, str]]:
    """Split a two-statement special query into _part1/_part2 units."""
    stmts = [s.strip() for s in sql.split(";") if s.strip()]
    if len(stmts) <= 1:
        return [(query_name, sql)]
    return [(f"{query_name}_part{i + 1}", stmt)
            for i, stmt in enumerate(stmts)]


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="nds_tpu.streams")
    p.add_argument("output_dir")
    p.add_argument("--streams", type=int, default=1)
    p.add_argument("--rngseed", type=int, required=True,
                   help="seed (the bench uses the load-test end timestamp)")
    p.add_argument("--template", type=int, default=None)
    p.add_argument("--template_dir", default=TEMPLATE_DIR)
    a = p.parse_args(argv)
    paths = generate_query_streams(a.output_dir, a.streams, a.rngseed,
                                   a.template_dir, a.template)
    print("\n".join(paths))
    return 0


if __name__ == "__main__":
    sys.exit(main())
