"""Per-query benchmark reports: status, timing, environment capture.

Capability parity with the reference's observability layer (reference
nds/PysparkBenchReport.py): wrap any callable, capture redacted env vars
(:71-72), engine configuration (the Spark-conf analog), wall time, a status
taxonomy — Completed / CompletedWithTaskFailures / Failed — and exceptions
(report_on :59-107); write ``{prefix}-{query}-{startTime}.json`` summaries
whose filename format downstream tooling depends on (write_summary
:109-122). The "task failure" analog on this engine is a device-backend
node falling back to the host oracle (collected per query), plus any
partial-shard errors once multi-host execution lands.
"""
from __future__ import annotations

import json
import os
import time
import traceback
from dataclasses import asdict, is_dataclass
from typing import Any, Callable


#: summary-layout version: bump when keys change shape so downstream
#: tooling can compare BENCH_r*.json / power summaries across rounds.
#: v2: adds schemaVersion itself, env.host capture, metrics, spans.
SCHEMA_VERSION = 2

REDACT_MARKERS = ("TOKEN", "SECRET", "PASSWORD", "PASSWD", "CREDENTIAL",
                  "APIKEY", "API_KEY", "AUTH")


def _redacted_env() -> dict[str, str]:
    out = {}
    for k, v in os.environ.items():
        if any(m in k.upper() for m in REDACT_MARKERS):
            v = "*********(redacted)"
        out[k] = v
    return out


def _host_capture() -> dict:
    """Redacted host/runtime capture: enough to explain a cross-round
    performance delta (CPU/arch/python/jax/backend) without leaking the
    host identity — the hostname rides only as a short hash so runs from
    the same machine are groupable but the name never lands in artifacts.
    """
    import hashlib
    import platform
    import socket

    out: dict = {
        "host_id": hashlib.sha1(
            socket.gethostname().encode()).hexdigest()[:10],
        "platform": platform.platform(),
        "machine": platform.machine(),
        "python": platform.python_version(),
        "cpu_count": os.cpu_count(),
    }
    try:        # report.py is imported by jax-less tools (datagen)
        import jax
        out["jax"] = jax.__version__
        out["jax_backend"] = jax.default_backend()
        out["device_count"] = jax.device_count()
    except Exception:
        pass
    return out


class BenchReport:
    """Collects one benchmark run's summary (one query, one table load...)."""

    def __init__(self, engine_config: Any = None, app_name: str = ""):
        cfg = {}
        if is_dataclass(engine_config):
            cfg = {k: str(v) for k, v in asdict(engine_config).items()}
        elif isinstance(engine_config, dict):
            cfg = {k: str(v) for k, v in engine_config.items()}
        self.summary = {
            "schemaVersion": SCHEMA_VERSION,
            "env": {
                "envVars": _redacted_env(),
                "host": _host_capture(),
                "engineConf": cfg,
                "appName": app_name,
            },
            "queryStatus": [],
            "exceptions": [],
            "startTime": None,
            "queryTimes": [],
            "taskFailures": [],
            # per-attempt records (resilience layer): attempts consumed per
            # report_on call, and the per-attempt status trail — a query
            # that failed transiently then completed reads
            # attempts=[2], retriedStatus=[["Failed", "Completed"]]
            "attempts": [],
            "retriedStatus": [],
        }

    def report_on(self, fn: Callable, *args, retry=None, **kwargs):
        """Run fn, recording wall time and status. Returns fn's result
        (or None on failure).

        retry: an optional resilience.RetryPolicy — transient failures
        re-run fn with deterministic backoff; every attempt's status lands
        in the summary (``attempts``/``retriedStatus``), and a retried-
        then-successful query records each failed attempt as a task
        failure, so finalize_status upgrades it to
        CompletedWithTaskFailures instead of a clean Completed.
        """
        self.summary["startTime"] = int(time.time() * 1000)
        start = time.perf_counter()
        result = None
        attempt_trail: list[str] = []
        while True:
            try:
                result = fn(*args, **kwargs)
                status = "Completed"
                attempt_trail.append(status)
                break
            except Exception as e:
                status = "Failed"
                attempt_trail.append(status)
                self.summary["exceptions"].append(traceback.format_exc())
                if retry is None or len(attempt_trail) >= retry.max_attempts \
                        or retry.classify(e) == "fatal":
                    break
                self.record_task_failure(
                    f"attempt {len(attempt_trail)} failed "
                    f"({type(e).__name__}); retrying")
                from .obs.metrics import RETRIES
                RETRIES.inc()
                time.sleep(retry.backoff(len(attempt_trail)))
        elapsed = int((time.perf_counter() - start) * 1000)
        if status == "Completed" and self.summary["taskFailures"]:
            status = "CompletedWithTaskFailures"
        self.summary["queryStatus"].append(status)
        self.summary["queryTimes"].append(elapsed)
        self.summary["attempts"].append(len(attempt_trail))
        self.summary["retriedStatus"].append(attempt_trail)
        return result

    def record_task_failure(self, detail: str) -> None:
        """Analog of the reference's Scala TaskFailureListener feed
        (reference nds/jvm_listener TaskFailureListener.scala): failures
        that did not abort the query but must surface in the status."""
        self.summary["taskFailures"].append(detail)

    def record_exec_stats(self, stats: dict) -> None:
        """Per-query device/host split (the Spark-UI job-group analog,
        reference nds_power.py:254): execution mode (record / compile+run /
        compiled / eager) and device milliseconds."""
        self.summary.setdefault("execStats", []).append(stats)

    def record_metrics(self, delta: dict) -> None:
        """Engine-metrics delta (obs.metrics.METRICS.delta over this unit
        of work): the uniform counters block every runner's JSON carries."""
        if delta:
            self.summary["metrics"] = delta

    def finalize_status(self) -> str:
        """Re-derive the last status after post-run failure recording (task
        failures land after report_on returns)."""
        if self.summary["queryStatus"] and self.summary["taskFailures"] \
                and self.summary["queryStatus"][-1] == "Completed":
            self.summary["queryStatus"][-1] = "CompletedWithTaskFailures"
        return self.summary["queryStatus"][-1] if \
            self.summary["queryStatus"] else "Failed"

    def write_summary(self, query_name: str, prefix: str = "") -> str | None:
        if not prefix:
            return None
        os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
        # filename format consumed by reporting pipelines
        # (reference PysparkBenchReport.py:116-118)
        path = f"{prefix}-{query_name}-{self.summary['startTime']}.json"
        with open(path, "w") as f:
            json.dump(self.summary, f, indent=2)
        return path
