"""nds_tpu — TPU-native decision-support benchmark framework on JAX/XLA.

A ground-up rebuild of the capability surface of NVIDIA's NDS v2.0 suite
(spark-rapids-benchmarks) for TPU: chunked data generation, CSV->Parquet load
test, seeded query-stream generation, a JAX/XLA columnar SQL engine (Power Run,
throughput streams, data maintenance), result validation, and a YAML-driven
orchestrator computing the NDS primary metric.
"""

__version__ = "0.1.0"
