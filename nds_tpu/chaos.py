"""Chaos campaigns: seeded fault injection against the LIVE query service.

The resilience layer (PR 1) gave the engine armable fault points and the
service (PR 10) gave it real concurrency — this module finally runs them
TOGETHER, the way a production engine earns trust: arm
``arrow.read``/``device.put``/``jax.compile``/``jax.execute``/
``stream.spawn``/``query.run`` specs while N concurrent clients are in
flight, and verify that resilience is a property of the whole stack:

- **bit-stability** — every response that COMPLETES under chaos is
  hash-identical to the fault-free baseline (a fault may fail a query,
  it must never corrupt one);
- **typed degradation** — every failure a client sees is a typed,
  classifiable error (FaultError, AdmissionRejected/CircuitOpen,
  DeadlineExceeded, ...), never a bare exception or a wedged lane;
- **post-mortem evidence** — the flight recorder dumps an artifact per
  firing and per circuit trip (the campaign zeroes the trip cooldown);
- **recovery** — after disarm, throughput returns toward the baseline
  (the ratio is recorded; asserting it belongs to quiet-host artifact
  runs, not 1-core CI).

Determinism: the campaign PLAN (which specs arm, in which scheduled
waves, with what actions/probabilities/caps) is a pure function of the
seed, each spec's probability draws come from its own arm-order-seeded
RNG (``FaultRegistry._seed_spec``), and the per-client workloads are
seeded — so two runs of one seed arm the same schedule and, with certain
(p=1, times-capped) specs, fire the same counts regardless of thread
interleaving. With one client the whole flight-event sequence replays.

``scripts/chaos_bench.py`` drives a 100-client campaign with every
point armed and records ``CHAOS_r01.json``; the CI ``chaos`` stage runs
a small seeded campaign at ~8 clients (tests/test_chaos.py).

The TRANSACTIONAL campaign (``run_txn_campaign``) points the same
machinery at a live warehouse: a writer thread commits multi-table
DML transactions while reader clients stream through the service, and
the ``manifest.write``/``txn.commit``/``txn.between_tables`` points
kill commits mid-flight. Its invariants extend the four above:

- **no torn manifest ever observed** — no reader or recovery path sees
  a half-written manifest/snapshot JSON (the atomic-rename contract);
- **snapshot-consistent reads** — every completed response is
  hash-identical to SOME published warehouse version replayed whole
  (``AS OF`` reference hashing), never a cross-table blend of two.
"""
from __future__ import annotations

import hashlib
import random
import threading
import time
from collections import Counter
from dataclasses import asdict, dataclass, field
from typing import Optional

from .obs.flight import FLIGHT
from .obs.metrics import METRICS
from .resilience import FAULT_POINTS, FAULTS, CircuitBreakerConfig, FaultSpec

#: exception type names (matched over the MRO, so subclasses count) a
#: chaos client is ALLOWED to see — the typed-degradation contract.
#: Anything else is an untyped escape and fails the campaign invariant.
TYPED_ERRORS = frozenset({
    "FaultError", "TransientError", "AdmissionRejected", "CircuitOpen",
    "ServiceClosed", "DeadlineExceeded", "TimeoutError",
})


def is_typed(exc: BaseException) -> bool:
    return bool({c.__name__ for c in type(exc).__mro__} & TYPED_ERRORS)


@dataclass
class CampaignSpec:
    """One seeded campaign's shape. Everything the firing schedule and
    workload depend on lives here, so the spec IS the reproducer."""
    seed: int = 0xC0FFEE
    clients: int = 8
    queries_per_client: int = 8
    #: fault points the plan arms (default: every registered point)
    points: tuple = FAULT_POINTS
    #: firings cap per armed spec (``times``): bounds the blast radius
    #: and, with probability 1.0, makes fired counts deterministic
    times_per_point: int = 2
    #: per-spec firing probability (1.0 = certain; <1 draws from the
    #: spec's own seeded RNG in its firing order)
    probability: float = 1.0
    #: actions the plan draws from per spec ("hang" only makes sense with
    #: the lane watchdog armed — see dispatch_timeout_s)
    actions: tuple = ("raise", "delay")
    #: a second scheduled wave arms after this fraction of the armed
    #: phase's queries complete (0 disables the pulse)
    pulse_at: float = 0.5
    #: per-query service deadline (seconds; 0 = none)
    deadline_s: float = 60.0
    #: client retry attempts for transient admission rejections
    admission_retries: int = 3
    # -- self-healing service knobs the campaign arms -----------------------
    breaker: bool = True
    breaker_open_s: float = 1.0
    breaker_min_failures: int = 4
    retry_budget: int = 64
    ticket_attempts: int = 2
    dispatch_timeout_s: float = 0.0
    #: flight artifacts directory (None = no dumps, ring only)
    dump_dir: Optional[str] = None

    def __post_init__(self):
        unknown = [p for p in self.points if p not in FAULT_POINTS]
        if unknown:
            raise ValueError(f"unknown fault points {unknown} "
                             f"(expected a subset of {FAULT_POINTS})")


@dataclass
class Wave:
    """One scheduled arming: ``at_fraction`` of the armed phase's traffic
    has completed when the wave's specs arm."""
    at_fraction: float
    specs: list = field(default_factory=list)   # [FaultSpec kwargs dicts]


def build_plan(spec: CampaignSpec) -> list[Wave]:
    """The deterministic firing schedule: a pure function of the spec.

    Wave 0 arms one spec per requested point at phase start; the pulse
    wave (``pulse_at``) re-arms the raise-style points mid-phase so the
    service is hit again AFTER its breaker/retry machinery has reacted
    to the first burst. Actions, delay durations, and the pulse point
    subset all come from one ``random.Random(seed)`` stream.
    """
    rng = random.Random(spec.seed)
    wave0 = Wave(at_fraction=0.0)
    for point in spec.points:
        action = spec.actions[rng.randrange(len(spec.actions))]
        seconds = round(rng.uniform(0.02, 0.15), 3) \
            if action in ("delay", "hang") else 0.0
        if action == "hang":    # bounded: the watchdog must outlive it
            seconds = max(seconds, 1.0)
        wave0.specs.append(dict(point=point, action=action,
                                seconds=seconds,
                                probability=spec.probability,
                                times=spec.times_per_point))
    waves = [wave0]
    if spec.pulse_at > 0:
        pulse = Wave(at_fraction=spec.pulse_at)
        pulse_points = [p for p in spec.points if rng.random() < 0.5]
        if not pulse_points:
            pulse_points = [spec.points[rng.randrange(len(spec.points))]]
        for point in pulse_points:
            pulse.specs.append(dict(point=point, action="raise",
                                    probability=spec.probability,
                                    times=max(1,
                                              spec.times_per_point // 2)))
        waves.append(pulse)
    return waves


def build_workload(spec: CampaignSpec, pool: list) -> dict[int, list]:
    """{client_id: [(label, sql)]}: seeded draws from the instantiation
    pool, one independent stream per client (dashboard shape: heavy
    cross-client repetition)."""
    out = {}
    for cid in range(spec.clients):
        rng = random.Random(f"{spec.seed}:workload:{cid}")
        out[cid] = [pool[rng.randrange(len(pool))]
                    for _ in range(spec.queries_per_client)]
    return out


def result_hash(table) -> str:
    """Stable content hash of a query result (rows are ordered — campaign
    templates carry ORDER BY)."""
    return hashlib.sha1(repr(table.to_pylist()).encode()).hexdigest()


class ChaosCampaign:
    """Drive one seeded campaign against a QueryService over ``session``.

    Three phases through ONE live service: fault-free ``baseline``
    (collects the reference hash per distinct text and the reference
    QPS), ``armed`` (the plan's waves arm on schedule while the clients
    run), and ``recovery`` (everything disarmed, QPS re-measured).
    """

    def __init__(self, spec: CampaignSpec, pool: list):
        self.spec = spec
        #: [(label, sql)] instantiation pool clients draw from
        self.pool = list(pool)
        self.plan = build_plan(spec)
        self._armed: list[FaultSpec] = []

    # -- phases --------------------------------------------------------------
    def _client(self, svc, cid: int, queries: list, state: dict) -> None:
        """One client thread: fire stream.spawn at startup (a chaos
        client IS a stream attempt — the spawn point kills client
        startups), then submit-and-wait each query, firing query.run the
        way the power runner does. Typed failures are recorded and the
        client moves on; transient admission rejections back off briefly
        and retry (the intended client response to overload)."""
        try:
            FAULTS.fire("stream.spawn", f"client{cid}")
        except Exception as e:
            # a killed client startup fails the whole client's stream,
            # typed; its queries still count toward the phase's schedule
            # thresholds so the driver never stalls on a dead client
            with state["lock"]:
                if is_typed(e):
                    state["typed"][type(e).__name__] += 1
                else:
                    state["untyped"].append(
                        f"client{cid} spawn: {type(e).__name__}: {e}")
                state["done"] += len(queries)
            return
        for label, sql in queries:
            err: Optional[BaseException] = None
            table = None
            for attempt in range(1 + self.spec.admission_retries):
                try:
                    FAULTS.fire("query.run", label)
                    t = svc.submit(sql, label=label,
                                   tenant=f"client{cid}",
                                   deadline_s=self.spec.deadline_s or None)
                    table = t.result(timeout=300)
                    err = None
                    break
                except Exception as e:
                    err = e
                    # only overload-shaped rejections are worth an
                    # immediate client retry; CircuitOpen classifies
                    # fatal (wait for a probe), faults just failed
                    names = {c.__name__ for c in type(e).__mro__}
                    if "AdmissionRejected" not in names \
                            or "CircuitOpen" in names:
                        break
                    time.sleep(0.01 * (attempt + 1))
            with state["lock"]:
                state["done"] += 1
                if err is None:
                    h = result_hash(table)
                    state["completed"] += 1
                    base = state["baseline_hashes"]
                    if base is not None and sql in base \
                            and base[sql] != h:
                        state["mismatches"].append(label)
                    state["hashes"].setdefault(sql, h)
                    state["all_hashes"].setdefault(sql, set()).add(h)
                elif is_typed(err):
                    state["typed"][type(err).__name__] += 1
                else:
                    state["untyped"].append(
                        f"{label}: {type(err).__name__}: {err}")

    def _run_phase(self, svc, name: str,
                   baseline_hashes: Optional[dict] = None,
                   driver=None) -> dict:
        workload = build_workload(self.spec, self.pool)
        total = sum(len(q) for q in workload.values())
        state = {"lock": threading.Lock(), "done": 0, "completed": 0,
                 "typed": Counter(), "untyped": [], "mismatches": [],
                 "hashes": {}, "all_hashes": {},
                 "baseline_hashes": baseline_hashes,
                 "total": total}
        FLIGHT.record("lifecycle_phase", phase=f"chaos:{name}",
                      status="start", clients=self.spec.clients)
        before = METRICS.snapshot()
        threads = [threading.Thread(
            target=self._client, args=(svc, cid, qs, state),
            name=f"chaos-client-{cid}", daemon=True)
            for cid, qs in workload.items()]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        if driver is not None:
            driver(state)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        delta = METRICS.delta(before)
        FLIGHT.record("lifecycle_phase", phase=f"chaos:{name}",
                      status="end", completed=state["completed"],
                      wall_s=round(wall, 3))
        return {"wall_s": round(wall, 3),
                "queries": total,
                "completed": state["completed"],
                "qps": round(state["completed"] / wall, 3) if wall else 0.0,
                "typed_failures": dict(state["typed"]),
                "untyped_failures": state["untyped"][:10],
                "untyped_count": len(state["untyped"]),
                "hash_mismatches": state["mismatches"][:10],
                "hash_mismatch_count": len(state["mismatches"]),
                "hashes": state["hashes"],
                # EVERY distinct hash observed per text (a client under a
                # moving warehouse legitimately sees several versions; the
                # txn campaign checks each against the per-version
                # reference set)
                "all_hashes": {s: sorted(hs)
                               for s, hs in state["all_hashes"].items()},
                "metrics_delta": delta}

    def _arm_wave(self, wave: Wave) -> None:
        for kw in wave.specs:
            self._armed.append(FAULTS.arm(FaultSpec(**kw)))

    def _driver(self, state: dict) -> None:
        """The scheduled-arming driver: waves arm when the completed
        fraction of the armed phase's traffic crosses their threshold
        (count-based, not time-based — the schedule is load-relative and
        replays across hosts of different speeds). Zero-threshold waves
        were already armed before the clients started (``stream.spawn``
        must be live when the first client fires it)."""
        waves = sorted((w for w in self.plan if w.at_fraction > 0),
                       key=lambda w: w.at_fraction)
        for wave in waves:
            while True:
                with state["lock"]:
                    done, total = state["done"], state["total"]
                if done >= wave.at_fraction * total:
                    break
                if done >= total:
                    return
                time.sleep(0.005)
            self._arm_wave(wave)

    def disarm(self) -> list[dict]:
        """Disarm every campaign spec; returns their fired counts (the
        measured firing schedule)."""
        fired = []
        for s in self._armed:
            fired.append({"point": s.point, "action": s.action,
                          "probability": s.probability, "times": s.times,
                          "fired": s.fired})
            FAULTS.disarm(s)
        self._armed = []
        return fired

    # -- the campaign --------------------------------------------------------
    def run(self, session, service_config=None) -> dict:
        """Run baseline -> armed -> recovery through one live service;
        returns the campaign record (the ``CHAOS_r*.json`` shape)."""
        from .service import QueryService, ServiceConfig

        spec = self.spec
        # the recorder is process-global: remember its settings so the
        # campaign's zeroed cooldown / private dump dir don't leak into
        # whatever runs after (restored in the finally below)
        prev_flight = (FLIGHT.enabled, FLIGHT.dump_dir,
                       FLIGHT.trip_cooldown_s)
        # ring sized so a whole campaign's lifecycle events fit: the
        # fault-event census and determinism comparisons read the ring
        capacity = max(4096,
                       80 * spec.clients * spec.queries_per_client)
        FLIGHT.configure(enabled=True, trip_cooldown_s=0.0,
                         capacity=capacity, clear=True)
        # explicit (configure treats None as "keep"): a dump-less campaign
        # must not inherit a previous run's artifact directory
        FLIGHT.dump_dir = spec.dump_dir
        cfg = service_config or ServiceConfig(
            max_pending=max(256, 4 * spec.clients),
            breaker=CircuitBreakerConfig(
                open_s=spec.breaker_open_s,
                min_failures=spec.breaker_min_failures)
            if spec.breaker else None,
            retry_budget=spec.retry_budget,
            ticket_attempts=spec.ticket_attempts,
            dispatch_timeout_s=spec.dispatch_timeout_s)
        try:
            with QueryService(session, cfg) as svc:
                # publish every template's shared program (record +
                # compile) so the armed phase exercises the batched path
                for _label, sql in self.pool:
                    svc.sql(sql, label="chaos_warm")
                    svc.sql(sql, label="chaos_warm")
                baseline = self._run_phase(svc, "baseline")
                # zero-threshold waves arm BEFORE the armed phase's
                # clients start (stream.spawn must be live for the first
                # client); the driver handles the scheduled >0 waves
                for wave in self.plan:
                    if wave.at_fraction <= 0:
                        self._arm_wave(wave)
                armed = self._run_phase(
                    svc, "armed", baseline_hashes=baseline["hashes"],
                    driver=self._driver)
                fired = self.disarm()
                recovery = self._run_phase(
                    svc, "recovery", baseline_hashes=baseline["hashes"])
        finally:
            self.disarm()
            (FLIGHT.enabled, FLIGHT.dump_dir,
             FLIGHT.trip_cooldown_s) = prev_flight
        fault_events = [
            {"point": e.get("point"), "detail": e.get("detail")}
            for e in FLIGHT.events() if e["event"] == "fault"]
        trip_events = [e for e in FLIGHT.events() if e["event"] == "trip"]
        firings = len(fault_events)
        dumps = list(FLIGHT.dumps)
        qps_ratio = (recovery["qps"] / baseline["qps"]) \
            if baseline["qps"] else None
        for phase in (baseline, armed, recovery):
            phase.pop("hashes")     # bulky; the comparison already ran
            phase.pop("all_hashes")
        record = {
            "schema_version": 1,
            "spec": asdict(spec),
            "plan": [{"at_fraction": w.at_fraction, "specs": w.specs}
                     for w in self.plan],
            "fired": fired,
            "phases": {"baseline": baseline, "armed": armed,
                       "recovery": recovery},
            "firings": firings,
            "firings_specs": armed["metrics_delta"].get(
                "fault_point_firings", 0),
            "fault_events": fault_events,
            "trips": len(trip_events),
            "flight_dumps": len(dumps),
            "flight_dump_paths": dumps[:20],
            "recovery_qps_ratio": round(qps_ratio, 4)
            if qps_ratio is not None else None,
            "invariants": {
                # the campaign's acceptance bar, evaluated inline so the
                # artifact is self-judging
                "all_failures_typed":
                    armed["untyped_count"] == 0
                    and recovery["untyped_count"] == 0
                    and baseline["untyped_count"] == 0,
                "completed_hash_identical":
                    armed["hash_mismatch_count"] == 0
                    and recovery["hash_mismatch_count"] == 0,
                "flight_dump_per_firing":
                    spec.dump_dir is None or len(dumps) >= firings,
                "qps_recovered_within_20pct":
                    qps_ratio is not None and qps_ratio >= 0.8,
            },
        }
        return record


def build_demo_session(work_dir: str, chunk_rows: int = 8192,
                       out_of_core_min_rows: int = 10_000,
                       **engine_kwargs):
    """A self-contained chaos target: synthetic fact/dim in-core tables
    (the batched-dispatch path) plus a parquet-backed streamed table (the
    serial/morsel path, so arrow.read and device.put fire per morsel).
    Used by scripts/chaos_bench.py and the CI campaign tests.

    Extra ``engine_kwargs`` flow into the EngineConfig (the frontdoor
    server process enables ``query_log=True`` this way so the bench can
    read latency from system.query_log over the wire)."""
    import os

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from .config import EngineConfig
    from .engine import Session

    os.makedirs(work_dir, exist_ok=True)
    rng = np.random.default_rng(23)
    n_fact, n_dim = 20_000, 50
    fact = pa.table({
        "fk": pa.array(rng.integers(0, n_dim, n_fact), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, n_fact), type=pa.int64()),
    })
    dim = pa.table({"dk": pa.array(np.arange(n_dim), type=pa.int64()),
                    "grp": pa.array((np.arange(n_dim) % 7)
                                    .astype(np.int64))})
    spath = os.path.join(work_dir, "sfact.parquet")
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 9, 60_000), type=pa.int32()),
        "v": pa.array(rng.integers(0, 1000, 60_000), type=pa.int64()),
    }), spath, row_group_size=chunk_rows)
    session = Session(EngineConfig(chunk_rows=chunk_rows,
                                   out_of_core_min_rows=out_of_core_min_rows,
                                   **engine_kwargs))
    session.register_arrow("fact", fact)
    session.register_arrow("dim", dim)
    session.register_parquet("sfact", spath)
    return session


def demo_pool() -> list:
    """The demo session's instantiation pool: one parameterized in-core
    template (compatible fingerprints -> batched dispatches) and one
    streamed scan (serial lane, morsel staging under fire)."""
    tpl = ("SELECT grp, COUNT(*) AS n, SUM(qty) AS tq FROM fact "
           "JOIN dim ON fk = dk WHERE qty BETWEEN {a} AND {b} "
           "GROUP BY grp ORDER BY grp")
    pool = [(f"incore#{i}", tpl.format(a=5 + i, b=60 + 2 * i))
            for i in range(6)]
    pool.append(("streamed#0",
                 "SELECT k, COUNT(*) AS n, SUM(v) AS sv FROM sfact "
                 "GROUP BY k ORDER BY k"))
    return pool


# -- the transactional campaign: chaos mid-DML over a live warehouse --------

#: the commit-path points the txn campaign arms by default
TXN_POINTS = ("manifest.write", "txn.commit", "txn.between_tables")


def txn_pool() -> list:
    """The warehouse demo's instantiation pool. Integer-only aggregates
    (bit-identical across the service lane and direct replay — the
    post-hoc verification hashes both) with the JOIN templates doing the
    heavy lifting: a cross-table blend of two warehouse versions (fact@v2
    joined to dim@v1) hashes unlike ANY single published version, so the
    snapshot-consistency check catches exactly the torn-commit failure."""
    tpl = ("SELECT grp, COUNT(*) AS n, SUM(qty) AS tq FROM wfact "
           "JOIN wdim ON fk = dk WHERE qty BETWEEN {a} AND {b} "
           "GROUP BY grp ORDER BY grp")
    pool = [(f"txnjoin#{i}", tpl.format(a=1 + i, b=70 + 3 * i))
            for i in range(5)]
    pool.append(("txnfact#0",
                 "SELECT COUNT(*) AS n, SUM(qty) AS tq FROM wfact"))
    pool.append(("txndim#0", "SELECT COUNT(*) AS n FROM wdim"))
    return pool


def build_txn_demo(work_dir: str):
    """A self-contained TRANSACTIONAL chaos target: a two-table warehouse
    seeded through one transaction (the snapshot log is live from version
    1), a WRITER session that owns the DML transactions, and a separate
    READER session over its own Warehouse handle — the topology snapshot
    isolation requires (the writer reads its own uncommitted writes; the
    reader pins to the published CURRENT and only advances on refresh).

    Returns ``(reader_session, writer_session, writer_warehouse, pool)``.
    """
    import os

    import numpy as np
    import pyarrow as pa

    from .config import EngineConfig
    from .engine import Session
    from .warehouse import Warehouse

    root = os.path.join(work_dir, "txn_wh")
    writer_wh = Warehouse(root)
    rng = np.random.default_rng(31)
    n_fact, n_dim = 6000, 40
    fact = pa.table({
        "fk": pa.array(rng.integers(0, n_dim, n_fact), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, n_fact), type=pa.int64()),
    })
    dim = pa.table({"dk": pa.array(np.arange(n_dim), type=pa.int64()),
                    "grp": pa.array((np.arange(n_dim) % 5)
                                    .astype(np.int64))})
    with writer_wh.transaction(committer="seed"):
        writer_wh.table("wfact").create(fact, partition=False)
        writer_wh.table("wdim").create(dim, partition=False)

    writer = Session(EngineConfig())
    writer.attach_warehouse(writer_wh)
    # staging sources the DML rounds insert from: plain in-core tables
    # (INSERT reads them; they are never versioned). The dim staging rows
    # reuse EXISTING join keys with fresh groups, so a dim insert changes
    # the join result — a fact@new/dim@old blend is hash-detectable.
    stage_fact = pa.table({
        "fk": pa.array(rng.integers(0, n_dim, 400), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, 400), type=pa.int64()),
    })
    stage_dim = pa.table({
        "dk": pa.array(np.arange(30) % n_dim, type=pa.int64()),
        "grp": pa.array(5 + (np.arange(30) % 3), type=pa.int64()),
    })
    writer.register_arrow("stage_fact", stage_fact)
    writer.register_arrow("stage_dim", stage_dim)

    reader = Session(EngineConfig())
    reader.attach_warehouse(Warehouse(root))
    return reader, writer, writer_wh, txn_pool()


def run_txn_campaign(spec: CampaignSpec, work_dir: str,
                     dml_rounds: int = 4) -> dict:
    """Baseline -> armed -> recovery against a LIVE warehouse: during the
    armed phase a writer thread commits two-table transactions (and
    aborts them when the armed commit-path points fire) while the reader
    clients stream through the service, refreshing their pinned snapshot
    after every round.

    The verdict is post-hoc and exhaustive: every published warehouse
    version is replayed WHOLE through a fresh ``AS OF``-pinned session,
    and every hash any client observed in any phase must equal one of
    those per-version references (``snapshot_consistent_reads``) — a
    response blending two versions, or reading an uncommitted write, has
    no matching reference and fails the campaign. ``dml_rounds`` must
    exceed the armed points' total firing budget so at least one
    transaction lands (``dml_progress``); 0 auto-scales to
    ``len(points) * times_per_point + 2``."""
    from .config import EngineConfig
    from .engine import Session
    from .service import QueryService, ServiceConfig
    from .warehouse import Warehouse

    if dml_rounds <= 0:
        dml_rounds = len(spec.points) * spec.times_per_point + 2
    reader, writer, writer_wh, pool = build_txn_demo(work_dir)
    root = writer_wh.root
    campaign = ChaosCampaign(spec, pool)
    prev_flight = (FLIGHT.enabled, FLIGHT.dump_dir, FLIGHT.trip_cooldown_s)
    capacity = max(4096, 80 * spec.clients * spec.queries_per_client)
    FLIGHT.configure(enabled=True, trip_cooldown_s=0.0,
                     capacity=capacity, clear=True)
    FLIGHT.dump_dir = spec.dump_dir
    cfg = ServiceConfig(
        max_pending=max(256, 4 * spec.clients),
        breaker=CircuitBreakerConfig(
            open_s=spec.breaker_open_s,
            min_failures=spec.breaker_min_failures)
        if spec.breaker else None,
        retry_budget=spec.retry_budget,
        ticket_attempts=spec.ticket_attempts,
        dispatch_timeout_s=spec.dispatch_timeout_s)
    dml = {"commits": 0, "aborts": 0, "errors": [], "refresh_errors": []}

    def dml_driver(state):
        """The writer thread body (runs beside the armed clients): each
        round is one atomic two-table transaction. A fired fault aborts
        the round — typed, rolled back, previous snapshot stays current —
        and the next round retries fresh. Readers advance only here,
        between rounds, via refresh (never mid-transaction)."""
        for i in range(dml_rounds):
            try:
                with writer_wh.transaction(committer=f"dml{i}"):
                    writer.execute(
                        "INSERT INTO wfact SELECT fk, qty FROM stage_fact"
                        f" WHERE qty <= {25 + 9 * i}")
                    writer.execute(
                        "INSERT INTO wdim SELECT dk, grp FROM stage_dim"
                        f" WHERE dk <= {10 + 7 * i}")
                dml["commits"] += 1
                writer.refresh_warehouse()
            except Exception as e:
                if is_typed(e):
                    dml["aborts"] += 1
                else:
                    dml["errors"].append(
                        f"dml{i}: {type(e).__name__}: {e}")
            try:
                reader.refresh_warehouse()
            except Exception as e:
                dml["refresh_errors"].append(
                    f"dml{i}: {type(e).__name__}: {e}")

    try:
        with QueryService(reader, cfg) as svc:
            for _label, sql in pool:
                svc.sql(sql, label="chaos_warm")
                svc.sql(sql, label="chaos_warm")
            baseline = campaign._run_phase(svc, "baseline")
            for wave in campaign.plan:
                if wave.at_fraction <= 0:
                    campaign._arm_wave(wave)
            # no baseline_hashes: under a moving warehouse the armed
            # phase's reference is the per-version replay below, not the
            # v1-only baseline
            armed = campaign._run_phase(svc, "armed", driver=dml_driver)
            fired = campaign.disarm()
            recovery = campaign._run_phase(svc, "recovery")
    finally:
        campaign.disarm()
        (FLIGHT.enabled, FLIGHT.dump_dir,
         FLIGHT.trip_cooldown_s) = prev_flight

    # -- post-hoc verdict ---------------------------------------------------
    # reopening runs recovery (the writer thread has exited; any dirty
    # abort's intent record is swept now) and then replays every published
    # version whole for the reference hash set
    verify_wh = Warehouse(root)
    versions = verify_wh.versions()
    allowed: dict[str, set] = {sql: set() for _l, sql in pool}
    for v in versions:
        s = Session(EngineConfig())
        s.attach_warehouse(Warehouse(root), at_version=v)
        for _label, sql in pool:
            allowed[sql].add(result_hash(s.sql(sql)))
    observed: dict[str, set] = {}
    for phase in (baseline, armed, recovery):
        for sql, hs in phase["all_hashes"].items():
            observed.setdefault(sql, set()).update(hs)
    stray = {sql: sorted(hs - allowed.get(sql, set()))
             for sql, hs in observed.items()
             if hs - allowed.get(sql, set())}

    corrupt_markers = ("corrupt warehouse manifest", "JSONDecodeError",
                       "Expecting value")

    def _torn(msgs):
        return [m for m in msgs
                if any(k in m for k in corrupt_markers)]

    torn = (_torn(dml["errors"]) + _torn(dml["refresh_errors"])
            + _torn(baseline["untyped_failures"])
            + _torn(armed["untyped_failures"])
            + _torn(recovery["untyped_failures"]))

    for phase in (baseline, armed, recovery):
        phase.pop("hashes")
        phase.pop("all_hashes")
    record = {
        "schema_version": 1,
        "mode": "txn",
        "spec": asdict(spec),
        "plan": [{"at_fraction": w.at_fraction, "specs": w.specs}
                 for w in campaign.plan],
        "fired": fired,
        "phases": {"baseline": baseline, "armed": armed,
                   "recovery": recovery},
        "dml": {"rounds": dml_rounds, "commits": dml["commits"],
                "aborts": dml["aborts"], "errors": dml["errors"][:10],
                "refresh_errors": dml["refresh_errors"][:10]},
        "warehouse_versions": versions,
        "current_version": verify_wh.current_version(),
        "txn_metrics": {
            k: armed["metrics_delta"].get(k, 0)
            for k in ("txn_commits", "txn_rollbacks", "txn_recoveries")},
        "stray_hashes": {sql: hs[:4] for sql, hs in stray.items()},
        "invariants": {
            "all_failures_typed":
                baseline["untyped_count"] == 0
                and armed["untyped_count"] == 0
                and recovery["untyped_count"] == 0
                and not dml["errors"],
            # every completed response equals SOME published version
            # replayed whole — never a cross-table blend of two
            "snapshot_consistent_reads": not stray,
            # no reader, refresh, or DML path ever parsed a half-written
            # manifest or snapshot record
            "no_torn_manifest_reads":
                not torn and not dml["refresh_errors"],
            "dml_progress": dml["commits"] >= 1,
        },
    }
    return record


# -- the topology campaign: chaos across PROCESS boundaries -----------------

#: the wire-layer points the topology campaign arms (inside the ENGINE
#: process, over the front door's remote ``chaos`` op)
TOPOLOGY_POINTS = ("frontdoor.drop", "frontdoor.kill")


def _spawn_frontdoor(extra_args: list, timeout_s: float = 120.0):
    """Spawn one engine process behind scripts/frontdoor_server.py and
    block until it prints its ``FRONTDOOR {json}`` readiness line.
    Returns ``(Popen, info_dict)``; close the child's stdin (or
    SIGTERM) to shut it down."""
    import json
    import os
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "frontdoor_server.py")
    proc = subprocess.Popen(
        [sys.executable, script] + list(extra_args),
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    if not line.startswith("FRONTDOOR "):
        proc.kill()
        raise RuntimeError(f"frontdoor server failed to start: {line!r}")
    return proc, json.loads(line.split(" ", 1)[1])


def _topology_phase(port: int, name: str, workload: dict,
                    baseline_hashes: dict, retries: int = 4,
                    tenant_of=None) -> dict:
    """Run one topology phase: each client is a thread owning its OWN
    FlightClient (persistent socket, bounded reconnect-retry), hashes
    come from the SERVER's canonical engine-table hash (``want_hash``) so
    completed responses compare bit-for-bit against the serial baseline
    across the process boundary."""
    from .service.frontdoor import FlightClient

    state = {"lock": threading.Lock(), "completed": 0,
             "typed": Counter(), "untyped": [], "mismatches": []}
    total = sum(len(q) for q in workload.values())

    def client(cid: int, queries: list) -> None:
        tenant = tenant_of(cid) if tenant_of else f"client{cid}"
        try:
            c = FlightClient("127.0.0.1", port, retries=retries)
        except Exception as e:
            with state["lock"]:
                if is_typed(e):
                    state["typed"][type(e).__name__] += len(queries)
                else:
                    state["untyped"].append(
                        f"client{cid} connect: {type(e).__name__}: {e}")
            return
        for label, sql in queries:
            try:
                _table, hdr = c.query(sql, tenant=tenant, label=label,
                                      want_hash=True)
            except Exception as e:
                with state["lock"]:
                    if is_typed(e):
                        state["typed"][type(e).__name__] += 1
                    else:
                        state["untyped"].append(
                            f"{label}: {type(e).__name__}: {e}")
                continue
            h = hdr.get("result_hash")
            with state["lock"]:
                state["completed"] += 1
                if sql in baseline_hashes and baseline_hashes[sql] != h:
                    state["mismatches"].append(label)
        c.close()

    threads = [threading.Thread(target=client, args=(cid, qs),
                                name=f"topo-client-{cid}", daemon=True)
               for cid, qs in workload.items()]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return {"phase": name, "wall_s": round(wall, 3), "queries": total,
            "completed": state["completed"],
            "typed_failures": dict(state["typed"]),
            "untyped_failures": state["untyped"][:10],
            "untyped_count": len(state["untyped"]),
            "hash_mismatches": state["mismatches"][:10],
            "hash_mismatch_count": len(state["mismatches"])}


def run_topology_campaign(spec: CampaignSpec, work_dir: str) -> dict:
    """The TOPOLOGY campaign: chaos across OS process boundaries.

    One engine process serves the demo dataset over the Arrow-IPC front
    door (fair queue + preemption + result cache armed); ``spec.clients``
    client THREADS in this process each own a FlightClient socket. Four
    phases through the wire:

    - ``clean``    — fault-free; every server hash must equal the serial
      in-process baseline (cross-process bit-identity);
    - ``drop``     — ``frontdoor.drop`` armed remotely (the wire chaos
      op): the server severs sockets instead of replying. Clients
      reconnect-and-retry; terminal failures must be typed
      (ConnectionDropped IS-A TransientError);
    - ``kill``     — ``frontdoor.kill:raise#1`` armed: the engine process
      ``os._exit``\\ s mid-query. Every client failure must still be
      typed, and the exit signature (86) is asserted;
    - ``recovery`` — a REPLACEMENT engine process binds the same port;
      clients complete fully and hashes still match.

    The stale-cache invariant rides the kill: a snapshot-warmed client
    cache (``warm_cache``) from the dead server must validate FALSE
    against the replacement (fresh epoch) — zero stale hits, re-fetch,
    hash-identical.
    """
    import os

    from .obs import metrics as _metrics
    from .service.frontdoor import ConnectionDropped, FlightClient

    pool = demo_pool()
    # serial in-process baseline: the reference hash per pool text (the
    # same canonical engine-table hashing the server ships per response)
    base_dir = os.path.join(work_dir, "baseline")
    os.makedirs(base_dir, exist_ok=True)
    base_session = build_demo_session(base_dir)
    baseline_hashes = {sql: result_hash(base_session.sql(sql))
                       for _label, sql in pool}

    server_args = ["--demo", "--allow_chaos", "--result_cache",
                   "--fair_queue", "--preemption",
                   "--tenant_weights", "interactive=4,batch=1"]
    rng = random.Random(spec.seed)

    def workload() -> dict:
        return {cid: [pool[rng.randrange(len(pool))]
                      for _ in range(spec.queries_per_client)]
                for cid in range(spec.clients)}

    phases = {}
    proc, info = _spawn_frontdoor(server_args)
    port = info["port"]
    try:
        phases["clean"] = _topology_phase(port, "clean", workload(),
                                          baseline_hashes)

        # warm a client-side cache from the live server's snapshot op:
        # post-kill these entries are STALE by construction (new epoch)
        cache_client = FlightClient("127.0.0.1", port, use_cache=True)
        warm_sql = pool[0][1]
        cache_client.query(warm_sql, label="cache_warm")
        warmed = cache_client.warm_cache()
        hits_before = _metrics.FRONTDOOR_CLIENT_CACHE_HITS.value

        ctl = FlightClient("127.0.0.1", port)

        def arm(specs: list) -> list:
            # the server configures BEFORE replying, and an armed drop
            # spec can sever the arm-reply itself — arming still took;
            # the reply's "fired" lists the REPLACED batch's counts
            try:
                return ctl.chaos(specs).get("fired", [])
            except ConnectionDropped:
                return []

        arm([f"frontdoor.drop:raise@{spec.probability}"
             f"#{spec.times_per_point * spec.clients}"])
        phases["drop"] = _topology_phase(port, "drop", workload(),
                                         baseline_hashes)
        fired = arm([])   # disarm; returns the drop spec's fired count

        # the kill: one engine-process os._exit mid-query. Clients see
        # severed sockets -> ConnectionDropped (typed); the phase runs
        # to completion against a dead server (bounded retries).
        arm(["frontdoor.kill:raise#1"])
        ctl.close()
        phases["kill"] = _topology_phase(port, "kill", workload(),
                                         baseline_hashes, retries=1)
        proc.stdin.close()
        kill_exit = proc.wait(timeout=60)

        # replacement engine process on the SAME port (SO_REUSEADDR):
        # the surviving cache_client reconnects to a fresh epoch
        proc, info = _spawn_frontdoor(server_args + ["--port", str(port)])
        phases["recovery"] = _topology_phase(port, "recovery", workload(),
                                             baseline_hashes)

        # stale-cache invariant: the warmed entry must validate FALSE
        # against the replacement server -> a real re-fetch, no client
        # cache hit, and the re-fetched hash still matches the baseline
        _t, hdr = cache_client.query(warm_sql, label="cache_probe",
                                     want_hash=True)
        stale_hits = (_metrics.FRONTDOOR_CLIENT_CACHE_HITS.value
                      - hits_before)
        probe_ok = (hdr.get("cache") != "client"
                    and hdr.get("result_hash") == baseline_hashes[warm_sql])
        cache_client.close()
    finally:
        try:
            proc.stdin.close()
            proc.wait(timeout=30)
        except Exception:
            proc.kill()

    total_untyped = sum(p["untyped_count"] for p in phases.values())
    total_mismatch = sum(p["hash_mismatch_count"] for p in phases.values())
    record = {
        "schema_version": 1,
        "mode": "topology",
        "spec": asdict(spec),
        "points": list(TOPOLOGY_POINTS),
        "phases": phases,
        "fired": fired,
        "kill_exit_code": kill_exit,
        "cache": {"warmed_entries": warmed, "stale_hits": stale_hits,
                  "revalidated_probe_ok": probe_ok},
        "invariants": {
            "all_failures_typed": total_untyped == 0,
            "completed_hash_identical": total_mismatch == 0,
            "engine_kill_observed": kill_exit == 86,
            "zero_stale_cache_hits": stale_hits == 0 and probe_ok,
            "recovered": phases["recovery"]["completed"]
            == phases["recovery"]["queries"]
            and phases["recovery"]["untyped_count"] == 0,
        },
    }
    return record
