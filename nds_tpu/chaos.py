"""Chaos campaigns: seeded fault injection against the LIVE query service.

The resilience layer (PR 1) gave the engine armable fault points and the
service (PR 10) gave it real concurrency — this module finally runs them
TOGETHER, the way a production engine earns trust: arm
``arrow.read``/``device.put``/``jax.compile``/``jax.execute``/
``stream.spawn``/``query.run`` specs while N concurrent clients are in
flight, and verify that resilience is a property of the whole stack:

- **bit-stability** — every response that COMPLETES under chaos is
  hash-identical to the fault-free baseline (a fault may fail a query,
  it must never corrupt one);
- **typed degradation** — every failure a client sees is a typed,
  classifiable error (FaultError, AdmissionRejected/CircuitOpen,
  DeadlineExceeded, ...), never a bare exception or a wedged lane;
- **post-mortem evidence** — the flight recorder dumps an artifact per
  firing and per circuit trip (the campaign zeroes the trip cooldown);
- **recovery** — after disarm, throughput returns toward the baseline
  (the ratio is recorded; asserting it belongs to quiet-host artifact
  runs, not 1-core CI).

Determinism: the campaign PLAN (which specs arm, in which scheduled
waves, with what actions/probabilities/caps) is a pure function of the
seed, each spec's probability draws come from its own arm-order-seeded
RNG (``FaultRegistry._seed_spec``), and the per-client workloads are
seeded — so two runs of one seed arm the same schedule and, with certain
(p=1, times-capped) specs, fire the same counts regardless of thread
interleaving. With one client the whole flight-event sequence replays.

``scripts/chaos_bench.py`` drives a 100-client campaign with all six
points armed and records ``CHAOS_r01.json``; the CI ``chaos`` stage runs
a small seeded campaign at ~8 clients (tests/test_chaos.py).
"""
from __future__ import annotations

import hashlib
import random
import threading
import time
from collections import Counter
from dataclasses import asdict, dataclass, field
from typing import Optional

from .obs.flight import FLIGHT
from .obs.metrics import METRICS
from .resilience import FAULT_POINTS, FAULTS, CircuitBreakerConfig, FaultSpec

#: exception type names (matched over the MRO, so subclasses count) a
#: chaos client is ALLOWED to see — the typed-degradation contract.
#: Anything else is an untyped escape and fails the campaign invariant.
TYPED_ERRORS = frozenset({
    "FaultError", "TransientError", "AdmissionRejected", "CircuitOpen",
    "ServiceClosed", "DeadlineExceeded", "TimeoutError",
})


def is_typed(exc: BaseException) -> bool:
    return bool({c.__name__ for c in type(exc).__mro__} & TYPED_ERRORS)


@dataclass
class CampaignSpec:
    """One seeded campaign's shape. Everything the firing schedule and
    workload depend on lives here, so the spec IS the reproducer."""
    seed: int = 0xC0FFEE
    clients: int = 8
    queries_per_client: int = 8
    #: fault points the plan arms (default: all six)
    points: tuple = FAULT_POINTS
    #: firings cap per armed spec (``times``): bounds the blast radius
    #: and, with probability 1.0, makes fired counts deterministic
    times_per_point: int = 2
    #: per-spec firing probability (1.0 = certain; <1 draws from the
    #: spec's own seeded RNG in its firing order)
    probability: float = 1.0
    #: actions the plan draws from per spec ("hang" only makes sense with
    #: the lane watchdog armed — see dispatch_timeout_s)
    actions: tuple = ("raise", "delay")
    #: a second scheduled wave arms after this fraction of the armed
    #: phase's queries complete (0 disables the pulse)
    pulse_at: float = 0.5
    #: per-query service deadline (seconds; 0 = none)
    deadline_s: float = 60.0
    #: client retry attempts for transient admission rejections
    admission_retries: int = 3
    # -- self-healing service knobs the campaign arms -----------------------
    breaker: bool = True
    breaker_open_s: float = 1.0
    breaker_min_failures: int = 4
    retry_budget: int = 64
    ticket_attempts: int = 2
    dispatch_timeout_s: float = 0.0
    #: flight artifacts directory (None = no dumps, ring only)
    dump_dir: Optional[str] = None

    def __post_init__(self):
        unknown = [p for p in self.points if p not in FAULT_POINTS]
        if unknown:
            raise ValueError(f"unknown fault points {unknown} "
                             f"(expected a subset of {FAULT_POINTS})")


@dataclass
class Wave:
    """One scheduled arming: ``at_fraction`` of the armed phase's traffic
    has completed when the wave's specs arm."""
    at_fraction: float
    specs: list = field(default_factory=list)   # [FaultSpec kwargs dicts]


def build_plan(spec: CampaignSpec) -> list[Wave]:
    """The deterministic firing schedule: a pure function of the spec.

    Wave 0 arms one spec per requested point at phase start; the pulse
    wave (``pulse_at``) re-arms the raise-style points mid-phase so the
    service is hit again AFTER its breaker/retry machinery has reacted
    to the first burst. Actions, delay durations, and the pulse point
    subset all come from one ``random.Random(seed)`` stream.
    """
    rng = random.Random(spec.seed)
    wave0 = Wave(at_fraction=0.0)
    for point in spec.points:
        action = spec.actions[rng.randrange(len(spec.actions))]
        seconds = round(rng.uniform(0.02, 0.15), 3) \
            if action in ("delay", "hang") else 0.0
        if action == "hang":    # bounded: the watchdog must outlive it
            seconds = max(seconds, 1.0)
        wave0.specs.append(dict(point=point, action=action,
                                seconds=seconds,
                                probability=spec.probability,
                                times=spec.times_per_point))
    waves = [wave0]
    if spec.pulse_at > 0:
        pulse = Wave(at_fraction=spec.pulse_at)
        pulse_points = [p for p in spec.points if rng.random() < 0.5]
        if not pulse_points:
            pulse_points = [spec.points[rng.randrange(len(spec.points))]]
        for point in pulse_points:
            pulse.specs.append(dict(point=point, action="raise",
                                    probability=spec.probability,
                                    times=max(1,
                                              spec.times_per_point // 2)))
        waves.append(pulse)
    return waves


def build_workload(spec: CampaignSpec, pool: list) -> dict[int, list]:
    """{client_id: [(label, sql)]}: seeded draws from the instantiation
    pool, one independent stream per client (dashboard shape: heavy
    cross-client repetition)."""
    out = {}
    for cid in range(spec.clients):
        rng = random.Random(f"{spec.seed}:workload:{cid}")
        out[cid] = [pool[rng.randrange(len(pool))]
                    for _ in range(spec.queries_per_client)]
    return out


def result_hash(table) -> str:
    """Stable content hash of a query result (rows are ordered — campaign
    templates carry ORDER BY)."""
    return hashlib.sha1(repr(table.to_pylist()).encode()).hexdigest()


class ChaosCampaign:
    """Drive one seeded campaign against a QueryService over ``session``.

    Three phases through ONE live service: fault-free ``baseline``
    (collects the reference hash per distinct text and the reference
    QPS), ``armed`` (the plan's waves arm on schedule while the clients
    run), and ``recovery`` (everything disarmed, QPS re-measured).
    """

    def __init__(self, spec: CampaignSpec, pool: list):
        self.spec = spec
        #: [(label, sql)] instantiation pool clients draw from
        self.pool = list(pool)
        self.plan = build_plan(spec)
        self._armed: list[FaultSpec] = []

    # -- phases --------------------------------------------------------------
    def _client(self, svc, cid: int, queries: list, state: dict) -> None:
        """One client thread: fire stream.spawn at startup (a chaos
        client IS a stream attempt — the spawn point kills client
        startups), then submit-and-wait each query, firing query.run the
        way the power runner does. Typed failures are recorded and the
        client moves on; transient admission rejections back off briefly
        and retry (the intended client response to overload)."""
        try:
            FAULTS.fire("stream.spawn", f"client{cid}")
        except Exception as e:
            # a killed client startup fails the whole client's stream,
            # typed; its queries still count toward the phase's schedule
            # thresholds so the driver never stalls on a dead client
            with state["lock"]:
                if is_typed(e):
                    state["typed"][type(e).__name__] += 1
                else:
                    state["untyped"].append(
                        f"client{cid} spawn: {type(e).__name__}: {e}")
                state["done"] += len(queries)
            return
        for label, sql in queries:
            err: Optional[BaseException] = None
            table = None
            for attempt in range(1 + self.spec.admission_retries):
                try:
                    FAULTS.fire("query.run", label)
                    t = svc.submit(sql, label=label,
                                   tenant=f"client{cid}",
                                   deadline_s=self.spec.deadline_s or None)
                    table = t.result(timeout=300)
                    err = None
                    break
                except Exception as e:
                    err = e
                    # only overload-shaped rejections are worth an
                    # immediate client retry; CircuitOpen classifies
                    # fatal (wait for a probe), faults just failed
                    names = {c.__name__ for c in type(e).__mro__}
                    if "AdmissionRejected" not in names \
                            or "CircuitOpen" in names:
                        break
                    time.sleep(0.01 * (attempt + 1))
            with state["lock"]:
                state["done"] += 1
                if err is None:
                    h = result_hash(table)
                    state["completed"] += 1
                    base = state["baseline_hashes"]
                    if base is not None and sql in base \
                            and base[sql] != h:
                        state["mismatches"].append(label)
                    state["hashes"].setdefault(sql, h)
                elif is_typed(err):
                    state["typed"][type(err).__name__] += 1
                else:
                    state["untyped"].append(
                        f"{label}: {type(err).__name__}: {err}")

    def _run_phase(self, svc, name: str,
                   baseline_hashes: Optional[dict] = None,
                   driver=None) -> dict:
        workload = build_workload(self.spec, self.pool)
        total = sum(len(q) for q in workload.values())
        state = {"lock": threading.Lock(), "done": 0, "completed": 0,
                 "typed": Counter(), "untyped": [], "mismatches": [],
                 "hashes": {}, "baseline_hashes": baseline_hashes,
                 "total": total}
        FLIGHT.record("lifecycle_phase", phase=f"chaos:{name}",
                      status="start", clients=self.spec.clients)
        before = METRICS.snapshot()
        threads = [threading.Thread(
            target=self._client, args=(svc, cid, qs, state),
            name=f"chaos-client-{cid}", daemon=True)
            for cid, qs in workload.items()]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        if driver is not None:
            driver(state)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        delta = METRICS.delta(before)
        FLIGHT.record("lifecycle_phase", phase=f"chaos:{name}",
                      status="end", completed=state["completed"],
                      wall_s=round(wall, 3))
        return {"wall_s": round(wall, 3),
                "queries": total,
                "completed": state["completed"],
                "qps": round(state["completed"] / wall, 3) if wall else 0.0,
                "typed_failures": dict(state["typed"]),
                "untyped_failures": state["untyped"][:10],
                "untyped_count": len(state["untyped"]),
                "hash_mismatches": state["mismatches"][:10],
                "hash_mismatch_count": len(state["mismatches"]),
                "hashes": state["hashes"],
                "metrics_delta": delta}

    def _arm_wave(self, wave: Wave) -> None:
        for kw in wave.specs:
            self._armed.append(FAULTS.arm(FaultSpec(**kw)))

    def _driver(self, state: dict) -> None:
        """The scheduled-arming driver: waves arm when the completed
        fraction of the armed phase's traffic crosses their threshold
        (count-based, not time-based — the schedule is load-relative and
        replays across hosts of different speeds). Zero-threshold waves
        were already armed before the clients started (``stream.spawn``
        must be live when the first client fires it)."""
        waves = sorted((w for w in self.plan if w.at_fraction > 0),
                       key=lambda w: w.at_fraction)
        for wave in waves:
            while True:
                with state["lock"]:
                    done, total = state["done"], state["total"]
                if done >= wave.at_fraction * total:
                    break
                if done >= total:
                    return
                time.sleep(0.005)
            self._arm_wave(wave)

    def disarm(self) -> list[dict]:
        """Disarm every campaign spec; returns their fired counts (the
        measured firing schedule)."""
        fired = []
        for s in self._armed:
            fired.append({"point": s.point, "action": s.action,
                          "probability": s.probability, "times": s.times,
                          "fired": s.fired})
            FAULTS.disarm(s)
        self._armed = []
        return fired

    # -- the campaign --------------------------------------------------------
    def run(self, session, service_config=None) -> dict:
        """Run baseline -> armed -> recovery through one live service;
        returns the campaign record (the ``CHAOS_r*.json`` shape)."""
        from .service import QueryService, ServiceConfig

        spec = self.spec
        # the recorder is process-global: remember its settings so the
        # campaign's zeroed cooldown / private dump dir don't leak into
        # whatever runs after (restored in the finally below)
        prev_flight = (FLIGHT.enabled, FLIGHT.dump_dir,
                       FLIGHT.trip_cooldown_s)
        # ring sized so a whole campaign's lifecycle events fit: the
        # fault-event census and determinism comparisons read the ring
        capacity = max(4096,
                       80 * spec.clients * spec.queries_per_client)
        FLIGHT.configure(enabled=True, trip_cooldown_s=0.0,
                         capacity=capacity, clear=True)
        # explicit (configure treats None as "keep"): a dump-less campaign
        # must not inherit a previous run's artifact directory
        FLIGHT.dump_dir = spec.dump_dir
        cfg = service_config or ServiceConfig(
            max_pending=max(256, 4 * spec.clients),
            breaker=CircuitBreakerConfig(
                open_s=spec.breaker_open_s,
                min_failures=spec.breaker_min_failures)
            if spec.breaker else None,
            retry_budget=spec.retry_budget,
            ticket_attempts=spec.ticket_attempts,
            dispatch_timeout_s=spec.dispatch_timeout_s)
        try:
            with QueryService(session, cfg) as svc:
                # publish every template's shared program (record +
                # compile) so the armed phase exercises the batched path
                for _label, sql in self.pool:
                    svc.sql(sql, label="chaos_warm")
                    svc.sql(sql, label="chaos_warm")
                baseline = self._run_phase(svc, "baseline")
                # zero-threshold waves arm BEFORE the armed phase's
                # clients start (stream.spawn must be live for the first
                # client); the driver handles the scheduled >0 waves
                for wave in self.plan:
                    if wave.at_fraction <= 0:
                        self._arm_wave(wave)
                armed = self._run_phase(
                    svc, "armed", baseline_hashes=baseline["hashes"],
                    driver=self._driver)
                fired = self.disarm()
                recovery = self._run_phase(
                    svc, "recovery", baseline_hashes=baseline["hashes"])
        finally:
            self.disarm()
            (FLIGHT.enabled, FLIGHT.dump_dir,
             FLIGHT.trip_cooldown_s) = prev_flight
        fault_events = [
            {"point": e.get("point"), "detail": e.get("detail")}
            for e in FLIGHT.events() if e["event"] == "fault"]
        trip_events = [e for e in FLIGHT.events() if e["event"] == "trip"]
        firings = len(fault_events)
        dumps = list(FLIGHT.dumps)
        qps_ratio = (recovery["qps"] / baseline["qps"]) \
            if baseline["qps"] else None
        for phase in (baseline, armed, recovery):
            phase.pop("hashes")     # bulky; the comparison already ran
        record = {
            "schema_version": 1,
            "spec": asdict(spec),
            "plan": [{"at_fraction": w.at_fraction, "specs": w.specs}
                     for w in self.plan],
            "fired": fired,
            "phases": {"baseline": baseline, "armed": armed,
                       "recovery": recovery},
            "firings": firings,
            "firings_specs": armed["metrics_delta"].get(
                "fault_point_firings", 0),
            "fault_events": fault_events,
            "trips": len(trip_events),
            "flight_dumps": len(dumps),
            "flight_dump_paths": dumps[:20],
            "recovery_qps_ratio": round(qps_ratio, 4)
            if qps_ratio is not None else None,
            "invariants": {
                # the campaign's acceptance bar, evaluated inline so the
                # artifact is self-judging
                "all_failures_typed":
                    armed["untyped_count"] == 0
                    and recovery["untyped_count"] == 0
                    and baseline["untyped_count"] == 0,
                "completed_hash_identical":
                    armed["hash_mismatch_count"] == 0
                    and recovery["hash_mismatch_count"] == 0,
                "flight_dump_per_firing":
                    spec.dump_dir is None or len(dumps) >= firings,
                "qps_recovered_within_20pct":
                    qps_ratio is not None and qps_ratio >= 0.8,
            },
        }
        return record


def build_demo_session(work_dir: str, chunk_rows: int = 8192,
                       out_of_core_min_rows: int = 10_000):
    """A self-contained chaos target: synthetic fact/dim in-core tables
    (the batched-dispatch path) plus a parquet-backed streamed table (the
    serial/morsel path, so arrow.read and device.put fire per morsel).
    Used by scripts/chaos_bench.py and the CI campaign tests."""
    import os

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from .config import EngineConfig
    from .engine import Session

    rng = np.random.default_rng(23)
    n_fact, n_dim = 20_000, 50
    fact = pa.table({
        "fk": pa.array(rng.integers(0, n_dim, n_fact), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, n_fact), type=pa.int64()),
    })
    dim = pa.table({"dk": pa.array(np.arange(n_dim), type=pa.int64()),
                    "grp": pa.array((np.arange(n_dim) % 7)
                                    .astype(np.int64))})
    spath = os.path.join(work_dir, "sfact.parquet")
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 9, 60_000), type=pa.int32()),
        "v": pa.array(rng.integers(0, 1000, 60_000), type=pa.int64()),
    }), spath, row_group_size=chunk_rows)
    session = Session(EngineConfig(chunk_rows=chunk_rows,
                                   out_of_core_min_rows=out_of_core_min_rows))
    session.register_arrow("fact", fact)
    session.register_arrow("dim", dim)
    session.register_parquet("sfact", spath)
    return session


def demo_pool() -> list:
    """The demo session's instantiation pool: one parameterized in-core
    template (compatible fingerprints -> batched dispatches) and one
    streamed scan (serial lane, morsel staging under fire)."""
    tpl = ("SELECT grp, COUNT(*) AS n, SUM(qty) AS tq FROM fact "
           "JOIN dim ON fk = dk WHERE qty BETWEEN {a} AND {b} "
           "GROUP BY grp ORDER BY grp")
    pool = [(f"incore#{i}", tpl.format(a=5 + i, b=60 + 2 * i))
            for i in range(6)]
    pool.append(("streamed#0",
                 "SELECT k, COUNT(*) AS n, SUM(v) AS sv FROM sfact "
                 "GROUP BY k ORDER BY k"))
    return pool
