"""Power-run workload: execute one query stream serially, timing each query.

Capability parity with the reference power runner (reference
nds/nds_power.py): stream parsing on ``-- start`` markers with the
two-statement splits (gen_sql_from_stream :49-76), table registration from
raw data or the Parquet warehouse (setup_tables :78-105), per-query timing
under a BenchReport with JSON summaries (run_one_query :124-134 +
PysparkBenchReport), output-column sanitization (ensure_valid_column_names
:136-173), a CSV time log with ``Power Start/End/Test Time`` sentinel rows
(:281-299), and a --sub_queries subset (:175-180).
"""
from __future__ import annotations

import argparse
import csv
import glob
import os
import re
import sys
import time
from collections import OrderedDict

from .engine import Session
from .config import EngineConfig
from .report import BenchReport
from .resilience import FAULTS, FaultSpec, RetryPolicy, run_with_deadline
from .schema import get_maintenance_schemas, get_schemas
from .streams import SPECIAL_TEMPLATES, split_special_query

_START_RE = re.compile(
    r"^--\s*start query (\d+) using template query(\d+)\.tpl", re.IGNORECASE)


def gen_sql_from_stream(stream_text: str) -> "OrderedDict[str, str]":
    """Split a stream file into {query_name: sql} preserving order."""
    queries: "OrderedDict[str, str]" = OrderedDict()
    current: list[str] = []
    number = None
    for line in stream_text.splitlines():
        m = _START_RE.match(line.strip())
        if m:
            if number is not None:
                _emit(queries, number, current)
            number = int(m.group(2))
            current = []
        else:
            current.append(line)
    if number is not None:
        _emit(queries, number, current)
    return queries


def strip_sql_comments(sql: str) -> str:
    """Drop full '--' comment lines: a ';' inside a template header comment
    (query93) must never reach the naive statement split used by the
    runners and the bench."""
    return "\n".join(ln for ln in sql.splitlines()
                     if not ln.lstrip().startswith("--"))


def _emit(queries, number, lines):
    sql = strip_sql_comments("\n".join(lines)).strip()
    name = f"query{number}"
    if number in SPECIAL_TEMPLATES:
        for part_name, part_sql in split_special_query(name, sql):
            queries[part_name] = part_sql
    else:
        queries[name] = sql.rstrip(";")


def setup_tables(session: Session, input_prefix: str, input_format: str,
                 use_decimal: bool = True,
                 maintenance: bool = False) -> dict[str, float]:
    """Register the 24 source tables (plus maintenance staging when asked).

    Returns per-table registration times (the reference times view creation,
    nds_power.py:94-104).
    """
    times: dict[str, float] = {}
    if input_format == "parquet" and glob.glob(
            os.path.join(input_prefix, "*", "manifest.json")):
        # warehouse layout (snapshot manifests): register pinned snapshots,
        # the reference's warehouse-catalog path (nds_power.py:107-121)
        from .warehouse import Warehouse
        t0 = time.perf_counter()
        Warehouse(input_prefix).register_all(session)
        times["warehouse"] = time.perf_counter() - t0
        return times
    schemas = dict(get_schemas(use_decimal))
    if maintenance:
        schemas.update(get_maintenance_schemas(use_decimal))
    for name, sch in schemas.items():
        path = os.path.join(input_prefix, name)
        if not os.path.exists(path):
            continue
        t0 = time.perf_counter()
        if input_format == "csv":
            session.register_csv(name, path,
                                 sch.arrow_schema(use_decimal=False))
        elif input_format == "parquet":
            session.register_parquet(name, path)
        else:
            raise ValueError(f"unsupported input format {input_format}")
        times[name] = time.perf_counter() - t0
    return times


_VALID_COL = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def ensure_valid_column_names(names: list[str]) -> list[str]:
    """Sanitize/dedupe output column names for parquet writing (reference
    nds_power.py:136-173)."""
    out: list[str] = []
    seen: dict[str, int] = {}
    for i, n in enumerate(names):
        if not n or not _VALID_COL.match(n):
            n = f"column_{i}"
        base = n
        if base in seen:
            seen[base] += 1
            n = f"{base}_{seen[base]}"
        else:
            seen[base] = 0
        out.append(n)
    return out


def run_one_query(session: Session, sql: str, query_name: str,
                  output_prefix: str | None, output_format: str,
                  backend: str | None = None):
    sql = strip_sql_comments(sql)   # callers may pass raw template text
    statements = [s for s in sql.split(";") if s.strip()]
    result = None
    for stmt in statements:
        # the query name labels spans and per-program device-time
        # attribution (obs.device_time): "query9/root" etc.
        result = session.sql(stmt, backend=backend, label=query_name)
    if output_prefix and result is not None:
        import pyarrow.parquet as pq
        from .engine.arrow_bridge import to_arrow
        table = to_arrow(result)
        table = table.rename_columns(
            ensure_valid_column_names(table.column_names))
        out_dir = os.path.join(output_prefix, query_name)
        os.makedirs(out_dir, exist_ok=True)
        pq.write_table(table, os.path.join(out_dir, "part-0.parquet"))
    return result


def run_query_stream(input_prefix: str, stream_path: str, time_log: str,
                     input_format: str = "parquet",
                     output_prefix: str | None = None,
                     output_format: str = "parquet",
                     json_summary_folder: str | None = None,
                     sub_queries: list[str] | None = None,
                     property_file: str | None = None,
                     backend: str | None = None,
                     warmup: int = 0,
                     strict: bool = False,
                     profile_folder: str | None = None,
                     fault_inject: list[str] | None = None,
                     keep_sc: bool = False,
                     decimal: str | None = None,
                     precompile: bool = True,
                     query_timeout: float | None = None,
                     query_attempts: int | None = None,
                     resume: bool = False,
                     late_mat: bool | None = None,
                     shared_scan: bool | None = None,
                     narrow_lanes: bool | None = None,
                     encoded_exec: bool | None = None,
                     verify_plans: str | None = None,
                     pallas_ops: str | None = None,
                     mesh_shards: int | None = None,
                     trace: str | None = None,
                     explain: bool = False,
                     query_log: str | None = None
                     ) -> list[tuple[str, int, int, int]]:
    """Run every query in the stream; returns (name, start_ms, end_ms, ms).

    The CSV time log layout (query name, start, end, elapsed + the
    ``Power Start/End/Test Time`` sentinel rows) matches the reference's
    (nds_power.py:281-299) so the orchestrator can scrape either.

    warmup: untimed pre-runs per query before the timed run (2 reaches the
    engine's compiled steady state: record pass + whole-plan compile).
    strict: raise at the end if any query fell back to the host oracle
    (the reference runs every op on the accelerator).
    profile_folder: write a jax.profiler trace per query under this folder
    (the Spark-UI job-group analog, reference nds_power.py:254).
    fault_inject: query names whose timed run raises an injected fault —
    sugar over the resilience FaultRegistry (``query.run`` raise-specs;
    SURVEY.md §5 failure-detection item; the reference only detects
    failures, it cannot inject them): the run must record ``Failed`` with
    the exception in the JSON summary and keep going, exactly like a
    genuine mid-stream query failure. Arbitrary engine-level faults arm
    via EngineConfig.fault_points / nds.tpu.fault_points instead.
    query_timeout: per-query wall-clock budget in seconds (None = take
    EngineConfig.query_timeout_s; 0 = unbounded). An overrun abandons the
    query mid-flight and records ``Failed`` (DeadlineExceeded) — a hung
    device call cannot stall the stream.
    query_attempts: timed attempts per query (None = take
    EngineConfig.query_attempts): transient failures retry with
    deterministic backoff; per-attempt statuses land in the JSON summary.
    resume: skip queries already recorded in an existing (flushed partial)
    time log — a multi-hour stream interrupted mid-run restarts where it
    stopped, keeping the original Power Start Time.
    narrow_lanes: --no_narrow_lanes A/B override (None = config): False
    restores the wide int64 morsel upload layout bit-identically.
    encoded_exec: --no_encoded_exec A/B override (None = config): False
    disables the dictionary/RLE wire encodings (streamed morsels ride the
    plain narrow-lane layout), bit-identical results.
    pallas_ops: comma list of {sort,groupby,gather} enabling the TPU
    Pallas kernel for that op family (None = take EngineConfig.pallas_ops;
    results are bit-identical to the XLA lowering either way).
    mesh_shards: partition every streamed scan group's morsels across this
    many data-parallel mesh replicas (shard_map per-morsel programs +
    one partial all_gather; None = take EngineConfig.mesh_shards, 0/1 =
    the unchanged single-chip path). Only out-of-core streamed queries
    shard; in-core queries stay single-chip.
    verify_plans: static plan-IR verification mode (off|final|per-pass,
    engine/verify.py) — None takes EngineConfig.verify_plans.
    trace: enable the obs span tracer for the whole stream and write a
    Chrome trace-event file (Perfetto) to this path at the end — the
    engine-internal complement of --profile_folder's jax traces.
    query_log: enable the durable query log (obs/query_log.py) and
    append one flat row per completed statement to this JSONL path
    (size-capped rotation) — the run leaves a self-describing artifact
    ``scripts/slo_report.py`` computes SLO attainment from offline, and
    ``system.query_log`` SQL works live against the same rows.
    explain: EXPLAIN ANALYZE mode (EngineConfig.profile_plans): every
    timed run executes profiled — the annotated per-plan-node tree (time
    %, rows est->act, bytes, memory peak) prints after each query and the
    profile JSON lands under <json_summary_folder>/explain/<query>.json
    for scripts/explain_report.py. Results stay bit-identical; walls
    measure the eager node-by-node walk, not the compiled steady state,
    so --explain runs are diagnostics, not benchmark numbers.
    """
    from .check import check_json_summary_folder, check_query_subset_exists
    from .config import maybe_enable_compile_cache
    from .obs.metrics import METRICS, QUERY_FAILURES
    from .obs.trace import TRACER

    maybe_enable_compile_cache()
    if trace:
        TRACER.configure(enabled=True)
    if query_log:
        from .obs.query_log import QUERY_LOG
        QUERY_LOG.configure(enabled=True, path=query_log, clear=False)
    if not resume:
        # a RESUMED run re-enters its own summary folder on purpose: the
        # already-written summaries belong to the very run being
        # continued, not to a stale previous one
        check_json_summary_folder(json_summary_folder)
    config = EngineConfig.from_property_file(property_file)
    from .config import apply_decimal
    apply_decimal(config, decimal)
    if late_mat is not None:     # --no_late_mat A/B override
        config.late_materialization = late_mat
    if shared_scan is not None:  # --no_shared_scan A/B override
        config.shared_scan = shared_scan
    if narrow_lanes is not None:  # --no_narrow_lanes A/B override
        config.narrow_lanes = narrow_lanes
    if encoded_exec is not None:  # --no_encoded_exec A/B override
        config.encoded_exec = encoded_exec
    if verify_plans is not None:  # --verify_plans override
        config.verify_plans = verify_plans
    if pallas_ops is not None:   # --pallas_ops A/B override
        config.pallas_ops = tuple(
            x.strip() for x in pallas_ops.split(",") if x.strip())
    if mesh_shards is not None:  # --mesh_shards override
        config.mesh_shards = mesh_shards
    if explain:                  # --explain: profiled timed runs
        config.profile_plans = True
    session = Session(config)
    setup_tables(session, input_prefix, input_format)

    with open(stream_path) as f:
        query_dict = gen_sql_from_stream(f.read())
    if sub_queries:
        check_query_subset_exists(query_dict, sub_queries)
        query_dict = OrderedDict(
            (k, v) for k, v in query_dict.items()
            if k in sub_queries
            or re.sub(r"_part[12]$", "", k) in sub_queries)

    timeout_s = config.query_timeout_s if query_timeout is None \
        else query_timeout
    attempts = config.query_attempts if query_attempts is None \
        else query_attempts
    retry = RetryPolicy(max_attempts=attempts,
                        backoff_s=config.retry_backoff_s) \
        if attempts and attempts > 1 else None

    rows: list[tuple[str, int, int, int]] = []
    done: set[str] = set()
    resumed_start: int | None = None
    resumed_end: int | None = None
    if resume and os.path.exists(time_log):
        rows, resumed_start, resumed_end = _read_partial_log(time_log)
        done = {r[0] for r in rows}
        if done:
            print(f"resume: {len(done)} queries already recorded in "
                  f"{time_log}; skipping them", flush=True)

    fallback_queries: dict[str, list[str]] = {}
    armed = [FAULTS.arm(FaultSpec(point="query.run", match=n))
             for n in (fault_inject or ())]

    def _injected(name: str) -> bool:
        base = re.sub(r"_part[12]$", "", name)
        return FAULTS.would_raise("query.run", name, aliases=(base,))

    try:
        # phase-structured cold start (warmup >= 1): record EVERY query
        # once, then compile all recorded programs through the tunnel
        # CONCURRENTLY (JaxExecutor.precompile_parallel) instead of
        # serial-at-second-run. The reference's analog is Spark planning at
        # ~ms per query (nds_power.py:124-134); here parallel compile RPCs
        # turn a cold stream's wall clock from sum(compiles) into
        # ~max(compiles).
        eff_warmup = warmup
        failed_records: set[str] = set()
        use_jax = (backend == "jax") if backend else config.use_jax
        # --explain executes eagerly node-by-node: there are no recorded
        # schedules to precompile, so the cold-start compile pass is moot
        if precompile and warmup >= 1 and use_jax and not explain:
            t0 = time.perf_counter()
            for name, sql in query_dict.items():
                if _injected(name) or name in done:
                    continue
                try:
                    run_one_query(session, sql, name, None, output_format,
                                  backend)
                except Exception:
                    # possibly transient: give this query its full
                    # per-query warmup back so the timed run is not a
                    # first-sighting eager outlier
                    failed_records.add(name)
                    continue
            t1 = time.perf_counter()
            res = session._jax_executor().precompile_parallel()
            compiled = sum(1 for v in res.values() if v == "compiled")
            recorded = sum(1 for n in query_dict
                           if not _injected(n) and n not in failed_records
                           and n not in done)
            print(f"precompile: recorded {recorded} queries in "
                  f"{t1 - t0:.1f}s; compiled {compiled}/{len(res)} programs "
                  f"in {time.perf_counter() - t1:.1f}s", flush=True)
            eff_warmup = warmup - 1

        power_start = resumed_start if resumed_start is not None \
            else int(time.time() * 1000)
        executed = 0
        for name, sql in query_dict.items():
            if name in done:
                continue
            executed += 1
            report = BenchReport(config, app_name=f"NDS-TPU {name}")
            base = re.sub(r"_part[12]$", "", name)
            # a failed/injected/timed-out run never reaches the session;
            # clear observability state so the report isn't stale
            session.last_fallbacks = []
            session.last_exec_stats = {}

            def run_fn(*a, _name=name, _base=base, **k):
                FAULTS.fire("query.run", _name, aliases=(_base,))
                return run_one_query(*a, **k)

            def attempt_fn(*a, _name=name, **k):
                from .obs.flight import FLIGHT
                from .resilience import DeadlineExceeded
                try:
                    return run_with_deadline(run_fn, timeout_s, *a,
                                             label=_name, **k)
                except DeadlineExceeded:
                    # the abandoned worker may still hold the session's
                    # statement lock (it cannot be killed): swap in fresh
                    # locks so the NEXT query runs now instead of queueing
                    # behind the zombie's hang — and flight-dump the
                    # moment (the service lane watchdog mirrors this move)
                    session.abandon_inflight()
                    FLIGHT.trip("query_watchdog", query=_name,
                                budget_s=timeout_s)
                    raise

            if not _injected(name):
                for _ in range(warmup if name in failed_records
                               else eff_warmup):
                    try:
                        run_one_query(session, sql, name, None,
                                      output_format, backend)
                    except Exception:
                        break  # the timed run reports the failure
            q_start = int(time.time() * 1000)
            metrics_before = METRICS.snapshot()
            if profile_folder:
                import jax
                os.makedirs(profile_folder, exist_ok=True)
                with jax.profiler.trace(os.path.join(profile_folder, name)):
                    report.report_on(attempt_fn, session, sql, name,
                                     output_prefix, output_format, backend,
                                     retry=retry)
            else:
                report.report_on(attempt_fn, session, sql, name,
                                 output_prefix, output_format, backend,
                                 retry=retry)
            for fb in session.last_fallbacks:
                report.record_task_failure(f"device fallback: {fb}")
            if session.last_fallbacks:
                fallback_queries[name] = list(session.last_fallbacks)
            if session.last_exec_stats:
                report.record_exec_stats(session.last_exec_stats)
            # per-query engine-counter delta: the uniform metrics block in
            # every JSON summary (queries_run, cache hits, retries, faults,
            # bytes uploaded... — obs.metrics glossary)
            report.record_metrics(METRICS.delta(metrics_before))
            if explain and session.last_profile is not None:
                # EXPLAIN ANALYZE artifacts: annotated tree to stdout, the
                # serialized profile beside the JSON summaries
                # (scripts/explain_report.py re-renders either)
                print(session.last_profile.render(), flush=True)
                if json_summary_folder:
                    import json as _json
                    exp_dir = os.path.join(json_summary_folder, "explain")
                    os.makedirs(exp_dir, exist_ok=True)
                    with open(os.path.join(exp_dir, f"{name}.json"),
                              "w") as f:
                        _json.dump(session.last_profile.to_dict(), f,
                                   indent=2)
            elapsed = report.summary["queryTimes"][-1]
            # same latency family the bench/service record into: top-K
            # slow templates rank live from the registry across runners
            METRICS.histogram("query_latency_ms",
                              template=name).observe(elapsed)
            rows.append((name, q_start, q_start + elapsed, elapsed))
            status = report.finalize_status()
            if status == "Failed":
                QUERY_FAILURES.inc()
            print(f"{name}: {status} in {elapsed} ms", flush=True)
            if json_summary_folder:
                report.write_summary(
                    name, prefix=os.path.join(json_summary_folder, "power"))
            # flush the partial log after every query: a multi-hour stream
            # interrupted mid-run keeps its measurements (sentinel rows are
            # appended only by the completed run below), and --resume
            # restarts from exactly this flushed state
            _write_time_log(time_log, power_start, rows, None)
        # resuming an already-complete log with nothing left to run keeps
        # the original Power End Time (rewriting it would inflate the
        # recorded Power Test Time)
        power_end = resumed_end if (executed == 0 and resumed_end is not None) \
            else int(time.time() * 1000)
        _write_time_log(time_log, power_start, rows, power_end)
    finally:
        for s in armed:
            FAULTS.disarm(s)
        if trace:
            TRACER.write_chrome_trace(trace)
            print(f"trace: {trace} (open in ui.perfetto.dev)", flush=True)
        if query_log:
            from .obs.query_log import QUERY_LOG
            QUERY_LOG.flush()
            print(f"query log: {query_log}", flush=True)
    if strict and fallback_queries:
        raise RuntimeError(
            "device fallbacks in strict mode: " + "; ".join(
                f"{q}: {fbs}" for q, fbs in fallback_queries.items()))
    return rows


def _read_partial_log(time_log: str) -> tuple[list, int | None, int | None]:
    """Parse a (possibly partial) power time log written by
    _write_time_log: per-query rows plus the Power Start/End sentinels
    (End present only if the run completed). The atomic
    flush-after-every-query contract means any existing log is a
    consistent prefix of the run — exactly what --resume needs."""
    rows: list[tuple[str, int, int, int]] = []
    power_start: int | None = None
    power_end: int | None = None
    with open(time_log) as f:
        for row in csv.reader(f):
            if not row or row[0] == "query":
                continue
            if row[0] == "Power Start Time":
                power_start = int(row[1])
            elif row[0] == "Power End Time":
                power_end = int(row[1])
            elif row[0] == "Power Test Time":
                continue
            else:
                rows.append((row[0], int(row[1]), int(row[2]), int(row[3])))
    return rows, power_start, power_end


def _write_time_log(time_log: str, power_start: int, rows, power_end) -> None:
    os.makedirs(os.path.dirname(time_log) or ".", exist_ok=True)
    tmp = time_log + ".tmp"
    with open(tmp, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["query", "start_time", "end_time", "time"])
        w.writerow(["Power Start Time", power_start, "", ""])
        for r in rows:
            w.writerow(r)
        if power_end is not None:
            w.writerow(["Power End Time", power_end, "", ""])
            w.writerow(["Power Test Time", "", "", power_end - power_start])
    os.replace(tmp, time_log)   # atomic: an interrupt never truncates


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="nds_tpu.power")
    p.add_argument("input_prefix", help="data root (per-table dirs)")
    p.add_argument("query_stream_file")
    p.add_argument("time_log")
    p.add_argument("--input_format", default="parquet",
                   choices=["parquet", "csv"])
    p.add_argument("--output_prefix", default=None)
    p.add_argument("--output_format", default="parquet")
    p.add_argument("--json_summary_folder", default=None)
    p.add_argument("--sub_queries", default=None,
                   help="comma-separated query subset, e.g. query1,query3")
    p.add_argument("--property_file", default=None)
    p.add_argument("--backend", default=None, choices=["jax", "numpy"])
    p.add_argument("--warmup", type=int, default=0,
                   help="untimed pre-runs per query (2 = compiled steady state)")
    p.add_argument("--strict", action="store_true",
                   help="fail if any query fell back to the host oracle")
    p.add_argument("--profile_folder", default=None,
                   help="write a jax.profiler trace per query here")
    p.add_argument("--fault_inject", default=None,
                   help="comma-separated query names whose run raises an "
                        "injected fault (harness self-test)")
    p.add_argument("--decimal", default=None, choices=["f64", "i64"],
                   help="decimal physical type (i64 = exact scaled int64, "
                        "the spec-faithful measured configuration)")
    p.add_argument("--no_precompile", action="store_true",
                   help="disable the record-all-then-compile-parallel cold "
                        "start (compiles lazily at second execution)")
    p.add_argument("--query_timeout", type=float, default=None,
                   help="per-query wall-clock budget in seconds (overrun "
                        "records Failed and the stream continues); default "
                        "from nds.tpu.query_timeout_s, 0 = unbounded")
    p.add_argument("--retry", type=int, default=None,
                   help="timed attempts per query (transient failures "
                        "retry with backoff); default from "
                        "nds.tpu.query_attempts")
    p.add_argument("--resume", action="store_true",
                   help="skip queries already recorded in the existing "
                        "(partial) time log and keep its Power Start Time")
    p.add_argument("--no_late_mat", action="store_true",
                   help="disable the late-materialization planner rewrite "
                        "(group by surrogate keys, gather dimension "
                        "attributes after aggregation) for A/B runs; "
                        "property: nds.tpu.late_materialization")
    p.add_argument("--verify_plans", default=None,
                   choices=["off", "final", "per-pass"],
                   help="static plan-IR verification (engine/verify.py): "
                        "verify rewrite-pass invariants on every planned "
                        "statement; per-pass attributes a violation to the "
                        "pass that introduced it. Default from "
                        "nds.tpu.verify_plans / NDS_TPU_VERIFY_PLANS "
                        "(CI runs final, bench runs off)")
    p.add_argument("--no_shared_scan", action="store_true",
                   help="disable shared-scan morsel fusion (one streaming "
                        "pass per big table per query serving every "
                        "branch) for A/B runs — each branch then streams "
                        "its table separately, the pre-round-7 behavior; "
                        "property: nds.tpu.shared_scan")
    p.add_argument("--no_narrow_lanes", action="store_true",
                   help="disable narrow-lane packed uploads (per-column "
                        "u8/u16/u32 morsel lanes chosen from column stats "
                        "+ bit-packed validity) for A/B runs — morsels "
                        "then ride the wide int64 layout, bit-identical "
                        "results; property: nds.tpu.narrow_lanes")
    p.add_argument("--no_encoded_exec", action="store_true",
                   help="disable encoded execution (dictionary/RLE wire "
                        "encodings chosen from cardinality/run stats, "
                        "code-space filters/joins/group-bys, per-site "
                        "decode) for A/B runs — streamed morsels then "
                        "ride the plain narrow-lane layout, bit-identical "
                        "results; property: nds.tpu.encoded_exec")
    p.add_argument("--pallas_ops", default=None, metavar="OPS",
                   help="comma list of {sort,groupby,gather}: enable the "
                        "hand-tiled TPU Pallas kernel for that op family "
                        "(engine/jax_backend/pallas_kernels.py), bit-"
                        "identical to the default XLA lowering; on non-TPU "
                        "backends kernels run in interpret mode (cpu) or "
                        "fall back with pallas_fallback_reason recorded; "
                        "property: nds.tpu.pallas_ops")
    p.add_argument("--mesh_shards", type=int, default=None, metavar="N",
                   help="multi-chip sharded morsel execution: partition "
                        "every streamed scan group's morsels across N "
                        "data-parallel replicas of the device mesh "
                        "(shard_map per-morsel programs, one partial "
                        "all_gather per morsel); 0/1 = single-chip, "
                        "bit-identical to leaving it unset; property: "
                        "nds.tpu.mesh_shards. Virtual-device testing: "
                        "XLA_FLAGS=--xla_force_host_platform_device_"
                        "count=N")
    p.add_argument("--explain", action="store_true",
                   help="EXPLAIN ANALYZE: run every timed query in "
                        "profiled mode (eager node-by-node walk, bit-"
                        "identical results) — prints the annotated plan "
                        "tree (time%%, rows est->act, bytes, memory peak) "
                        "per query and writes profile JSONs under "
                        "<json_summary_folder>/explain/ for "
                        "scripts/explain_report.py; walls are diagnostic, "
                        "not the compiled steady state")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="enable engine span tracing for the whole stream "
                        "and write a Chrome trace-event file here (opens "
                        "in ui.perfetto.dev); per-query engine metrics "
                        "land in the JSON summaries either way")
    p.add_argument("--query_log", default=None, metavar="PATH",
                   help="enable the durable query log and append one "
                        "flat JSONL row per completed statement here "
                        "(size-capped rotation; scripts/slo_report.py "
                        "reads it offline, system.query_log SQL live)")
    a = p.parse_args(argv)
    sub = a.sub_queries.split(",") if a.sub_queries else None
    inject = a.fault_inject.split(",") if a.fault_inject else None
    run_query_stream(a.input_prefix, a.query_stream_file, a.time_log,
                     a.input_format, a.output_prefix, a.output_format,
                     a.json_summary_folder, sub, a.property_file, a.backend,
                     warmup=a.warmup, strict=a.strict,
                     profile_folder=a.profile_folder, fault_inject=inject,
                     decimal=a.decimal, precompile=not a.no_precompile,
                     query_timeout=a.query_timeout, query_attempts=a.retry,
                     resume=a.resume,
                     late_mat=False if a.no_late_mat else None,
                     shared_scan=False if a.no_shared_scan else None,
                     narrow_lanes=False if a.no_narrow_lanes else None,
                     encoded_exec=False if a.no_encoded_exec else None,
                     verify_plans=a.verify_plans,
                     pallas_ops=a.pallas_ops,
                     mesh_shards=a.mesh_shards,
                     trace=a.trace,
                     explain=a.explain,
                     query_log=a.query_log)
    return 0


if __name__ == "__main__":
    sys.exit(main())
