"""Multi-chip execution: device meshes + distributed relational primitives.

The reference scales SQL via Spark's block shuffle between executors
(SURVEY.md §2 parallelism table; no NCCL/MPI — JVM netty shuffle). The
TPU-native equivalents here ride XLA collectives over ICI/DCN:

- all_to_all      == shuffle / hash repartition
- all_gather      == broadcast join of dimension tables
- psum / psum_scatter == partial-aggregate merge
- row-sharded arrays over a Mesh == table partitions across executors
"""
from .mesh import make_mesh, replicated_spec, shard_spec  # noqa: F401
from .dist_ops import (  # noqa: F401
    shard_rows, broadcast_join_aggregate, gather_partials,
    repartition_by_key, distributed_aggregate,
)
