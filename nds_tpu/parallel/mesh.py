"""Device mesh construction for table sharding.

One logical axis ("shards") carries data-parallel table partitioning — the
analog of the reference's executor count (reference nds/base.template
NUM_EXECUTORS x EXECUTOR_CORES; here chips on ICI). A second optional axis
("streams") multiplexes concurrent query streams onto disjoint sub-slices
for the throughput test (reference nds/nds-throughput runs N OS processes).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_shards: Optional[int] = None,
              devices: Optional[Sequence] = None,
              axis_name: str = "shards") -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_shards is not None:
        if len(devs) < n_shards:
            raise ValueError(
                f"need {n_shards} devices, have {len(devs)} "
                "(for tests set XLA_FLAGS=--xla_force_host_platform_device_count)")
        devs = devs[:n_shards]
    import numpy as np
    return Mesh(np.asarray(devs), (axis_name,))


def shard_spec(mesh: Mesh) -> NamedSharding:
    """Row-sharded over the mesh's first axis."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
