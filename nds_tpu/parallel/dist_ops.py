"""Distributed relational primitives over a device mesh (shard_map + collectives).

Spark-shuffle analogs, TPU-native (SURVEY.md §2 last row):
- `repartition_by_key`   all_to_all hash shuffle of row blocks
- `broadcast_join_aggregate`  replicated build side (all_gather-free: the
  dimension table is small, so it rides in replicated sharding), sharded
  probe side, local partial aggregation, psum merge — the classic
  "broadcast join + partial agg" Spark plan for star-schema queries.
- `distributed_aggregate`  local partial agg -> all_gather of bounded
  partials -> replicated final merge (Spark partial/final aggregate).

Everything is a single jittable SPMD program: static shapes, masked rows,
collectives inserted explicitly via shard_map.
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.jax_backend import kernels


def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
    """Version-portable shard_map: newer jax exports it top-level with a
    `check_vma` kwarg; older releases keep it in jax.experimental with the
    same knob named `check_rep`. Every call site in this tree routes
    through here so the mesh path runs on both."""
    try:
        from jax import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check_vma)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)

_I32 = jnp.int32


def shard_rows(arrays: list[jax.Array], alive: jax.Array, mesh: Mesh
               ) -> tuple[list[jax.Array], jax.Array]:
    """Pad row count to a multiple of the mesh size and row-shard everything."""
    n_shards = mesh.devices.size
    axis = mesh.axis_names[0]
    cap = int(alive.shape[0])
    padded = ((cap + n_shards - 1) // n_shards) * n_shards
    sharding = NamedSharding(mesh, P(axis))

    def pad(x):
        if x.shape[0] != padded:
            fill = jnp.zeros((padded - x.shape[0],) + x.shape[1:], x.dtype)
            x = jnp.concatenate([x, fill])
        return jax.device_put(x, sharding)

    return [pad(a) for a in arrays], pad(alive)


def _multi_hash(keys: list[jax.Array], n_shards: int) -> jax.Array:
    """Shard assignment over a composite key (mix-fold each column)."""
    h = jnp.zeros(keys[0].shape, jnp.uint32)
    for k in keys:
        h = h * jnp.uint32(1000003) + (k.astype(jnp.uint32)
                                       * jnp.uint32(2654435761) >> 13)
    return (h % jnp.uint32(n_shards)).astype(_I32)


def _as_key_list(key) -> list[jax.Array]:
    return list(key) if isinstance(key, (list, tuple)) else [key]


def repartition_by_key(mesh: Mesh, per_pair_capacity: int,
                       emit_key: bool = True):
    """Build a jittable all_to_all hash-repartition over `mesh`.

    Returned fn maps (columns, alive, key) — all row-sharded — to the same
    pytree with every row now living on shard hash(key) % n_shards, plus an
    int32 overflow counter (rows dropped because a (src,dst) block exceeded
    per_pair_capacity; callers must size capacity so this stays 0).
    `key` may be one array or a list of arrays (composite shuffle key: the
    hash mixes every column, the returned key is the first).
    emit_key=False skips the separate exchanged key output (the alive mask
    is returned in its slot) — join lowering already carries the key inside
    `columns`, and the duplicate would cross the ICI once per run.
    """
    axis = mesh.axis_names[0]
    n_shards = mesh.devices.size

    def local(cols, alive, key):
        keys = _as_key_list(key)
        cap = alive.shape[0]
        dest = jnp.where(alive, _multi_hash(keys, n_shards), n_shards)
        key = keys[0]
        # rank of each row within its destination block
        order = jnp.argsort(dest, stable=True)
        dest_sorted = dest[order]
        boundary = jnp.concatenate(
            [jnp.ones(1, bool), dest_sorted[1:] != dest_sorted[:-1]])
        pos_in_block = jnp.arange(cap, dtype=_I32) - \
            lax.cummax(jnp.where(boundary, jnp.arange(cap, dtype=_I32), 0),
                       axis=0)
        slot_sorted = pos_in_block
        overflow = jnp.sum((slot_sorted >= per_pair_capacity) &
                           (dest_sorted < n_shards)).astype(_I32)
        # scatter rows into [n_shards, per_pair_capacity] blocks
        ok = (slot_sorted < per_pair_capacity) & (dest_sorted < n_shards)
        flat = jnp.where(ok, dest_sorted * per_pair_capacity + slot_sorted,
                         n_shards * per_pair_capacity)

        def place(col_sorted):
            buf = jnp.zeros((n_shards * per_pair_capacity + 1,),
                            col_sorted.dtype)
            zero = jnp.zeros((), col_sorted.dtype)   # keep bool cols bool
            return buf.at[flat].set(jnp.where(ok, col_sorted, zero)
                                    )[:n_shards * per_pair_capacity]

        out_cols = [place(c[order]) for c in cols]
        out_alive = jnp.zeros(n_shards * per_pair_capacity + 1, bool).at[
            flat].set(ok)[:n_shards * per_pair_capacity]
        out_key = place(key[order]) if emit_key else out_alive
        # exchange: block b of this shard -> shard b
        def exchange(x):
            blocks = x.reshape((n_shards, per_pair_capacity) + x.shape[1:])
            return lax.all_to_all(blocks, axis, split_axis=0, concat_axis=0
                                  ).reshape((-1,) + x.shape[1:])
        out_cols = [exchange(c) for c in out_cols]
        out_alive = exchange(out_alive)
        out_key = exchange(out_key) if emit_key else out_alive
        overflow = lax.psum(overflow, axis)
        return out_cols, out_alive, out_key, overflow

    return shard_map(local, mesh=mesh,
                     in_specs=(P(axis), P(axis), P(axis)),
                     out_specs=(P(axis), P(axis), P(axis), P()))


def gather_partials(mesh: Mesh):
    """Jittable all_gather of a row-sharded pytree of per-replica partial
    blocks into a replicated concatenation (tiled: shard k's rows land at
    block k). The engine's sharded morsel path dispatches this as its ONE
    collective per morsel: device-local partial aggregates are bounded
    (group-cardinality-sized), so only the decomposed partials ride the
    ICI before the existing host-side final merge
    (jax_backend/shard_exec.ShardedMorselQuery)."""
    axis = mesh.axis_names[0]

    def local(tree):
        return jax.tree_util.tree_map(
            lambda x: lax.all_gather(x, axis, tiled=True), tree)

    return shard_map(local, mesh=mesh, in_specs=(P(axis),), out_specs=P(),
                     check_vma=False)


def _local_join_ranges(lkd, lal, rkd, ral):
    """Per-shard probe ranges for a co-partitioned join block (the generic
    sort-based machinery, shard-local): returns (lo, cnt, perm_r)."""
    lcap, rcap = lal.shape[0], ral.shape[0]
    kd = [jnp.concatenate([a, b]) for a, b in zip(lkd, rkd)]
    al = jnp.concatenate([lal, ral])
    gid, _ = kernels.dense_rank(
        kd, [jnp.ones(lcap + rcap, bool)] * len(kd), al)
    l_gid, r_gid = gid[:lcap], gid[lcap:]
    _, perm_r = kernels.build_side(
        jnp.where(al[lcap:], r_gid, jnp.iinfo(_I32).max), ral)
    lo, cnt = kernels.probe_counts_by_gid(r_gid, ral, l_gid, lal,
                                          gid_cap=lcap + rcap)
    return lo, cnt, perm_r


def shuffle_join_counts(mesh: Mesh):
    """Jittable per-shard probe ranges + match totals of a co-partitioned
    (repartitioned) join: (lkeys, lalive, rkeys, ralive) -> ((n_shards,)
    counts, lo, cnt, perm_r) — the ranges feed shuffle_join_expand so the
    dominant per-shard sort happens ONCE."""
    axis = mesh.axis_names[0]

    def local(lkd, lal, rkd, ral):
        lo, cnt, perm_r = _local_join_ranges(list(lkd), lal, list(rkd), ral)
        return jnp.sum(cnt).reshape(1), lo, cnt, perm_r

    return shard_map(local, mesh=mesh,
                     in_specs=(P(axis), P(axis), P(axis), P(axis)),
                     out_specs=(P(axis),) * 4, check_vma=False)


def shuffle_join_expand(mesh: Mesh, cap_out_shard: int):
    """Jittable shard-local inner-join expansion over co-partitioned sides,
    reusing the probe ranges from shuffle_join_counts.

    (lo, cnt, perm_r, lalive, lcols, rcols) -> (out_lcols, out_rcols,
    out_alive), each sharded with cap_out_shard rows per shard. Together
    with repartition_by_key this is the Spark partitioned shuffle join
    (SURVEY.md §2 parallelism table last row): only hash-routed blocks ride
    the ICI — the fact sides are never gathered."""
    axis = mesh.axis_names[0]

    def local(lo, cnt, perm_r, lal, lcols, rcols):
        rcap = perm_r.shape[0]
        left_idx, build_pos, alive_out = kernels.expand_join(
            lo, cnt, lal, cap_out_shard)
        right_rows = perm_r[jnp.clip(build_pos, 0, rcap - 1)]
        out_l = tuple(c[left_idx] for c in lcols)
        out_r = tuple(c[right_rows] for c in rcols)
        return out_l, out_r, alive_out

    return shard_map(local, mesh=mesh,
                     in_specs=(P(axis),) * 6,
                     out_specs=(P(axis), P(axis), P(axis)), check_vma=False)


def _partial_agg(spec: str, v, contrib, gid, n_partial):
    sg = jnp.where(contrib, gid, n_partial)
    if spec == "count":
        return jax.ops.segment_sum(jnp.where(contrib, 1, 0).astype(v.dtype),
                                   sg, num_segments=n_partial)
    if spec == "sum":
        return jax.ops.segment_sum(jnp.where(contrib, v, 0), sg,
                                   num_segments=n_partial)
    if spec in ("min", "max"):
        ext = kernels._extreme(v.dtype, spec)
        seg = jax.ops.segment_min if spec == "min" else jax.ops.segment_max
        return seg(jnp.where(contrib, v, ext), sg, num_segments=n_partial)
    raise ValueError(spec)


def _merge_agg(spec: str, p, g_alive, m_gid, cap_out):
    sg = jnp.where(g_alive, m_gid, cap_out)
    if spec in ("sum", "count"):
        return jax.ops.segment_sum(jnp.where(g_alive, p, 0), sg,
                                   num_segments=cap_out)
    ext = kernels._extreme(p.dtype, spec)
    seg = jax.ops.segment_min if spec == "min" else jax.ops.segment_max
    return seg(jnp.where(g_alive, p, ext), sg, num_segments=cap_out)


def distributed_aggregate(mesh: Mesh, n_partial: int, specs: list[str],
                          n_keys: int = 1):
    """Partial-aggregate per shard, all_gather bounded partials, final merge.

    specs: per-value aggregation kind, "sum"|"count"|"min"|"max".
    Returned jittable fn: (group_keys [sharded; one array or a list of
    n_keys arrays — composite GROUP BY], valid (same shape), alive, values)
    -> (group_keys, key_valids [False marks a NULL group key — the key
    array's raw value is meaningless there], agg_values, out_alive,
    overflow) replicated, n_partial * n_shards rows each; overflow counts
    rows in groups beyond n_partial (callers must size n_partial so it
    stays 0 — otherwise results are partial). Single-key callers get single
    key/valid arrays back.
    """
    axis = mesh.axis_names[0]

    def local(keys, valids, alive, values):
        keys, valids = _as_key_list(keys), _as_key_list(valids)
        single = len(keys) == 1
        gid, _ = kernels.dense_rank(keys, valids, alive)
        cap = alive.shape[0]
        # rows in groups beyond the partial capacity would be silently
        # dropped by the out-of-range scatter — count them instead
        overflow = jnp.sum((alive & (gid >= n_partial) & (gid < cap))
                           .astype(_I32))
        reps, rep_valids = [], []
        for k, kv in zip(keys, valids):
            r, rv = kernels.group_representatives(gid, alive, k, kv,
                                                  n_partial)
            reps.append(r)
            rep_valids.append(rv)
        # slot occupancy is "some alive row landed here" — NOT any key's
        # validity (a group whose first GROUP BY key is NULL still exists)
        occ = jnp.zeros(n_partial + 1, bool).at[
            jnp.where(alive & (gid < n_partial), gid, n_partial)
        ].set(True)[:n_partial]
        contrib = alive
        partials = [_partial_agg(spec, v, contrib, gid, n_partial)
                    for spec, v in zip(specs, values)]
        # gather all shards' partials everywhere, merge locally (replicated)
        g_keys = [lax.all_gather(r, axis, tiled=True) for r in reps]
        g_valids = [lax.all_gather(rv, axis, tiled=True)
                    for rv in rep_valids]
        g_occ = lax.all_gather(occ, axis, tiled=True)
        g_partials = [lax.all_gather(p, axis, tiled=True) for p in partials]
        m_gid, _ = kernels.dense_rank(g_keys, g_valids, g_occ)
        cap_out = g_keys[0].shape[0]
        out_keys, out_valids = [], []
        for gk, gv in zip(g_keys, g_valids):
            ok, ov = kernels.group_representatives(m_gid, g_occ, gk, gv,
                                                   cap_out)
            out_keys.append(ok)
            out_valids.append(ov)
        out_alive = jnp.zeros(cap_out + 1, bool).at[
            jnp.where(g_occ, m_gid, cap_out)].set(True)[:cap_out]
        merged = [_merge_agg(spec, p, g_occ, m_gid, cap_out)
                  for spec, p in zip(specs, g_partials)]
        keys_out = out_keys[0] if single else out_keys
        valids_out = out_valids[0] if single else out_valids
        return keys_out, valids_out, merged, out_alive, \
            lax.psum(overflow, axis)

    return shard_map(local, mesh=mesh,
                     in_specs=(P(axis), P(axis), P(axis), P(axis)),
                     out_specs=(P(), P(), P(), P(), P()), check_vma=False)


def broadcast_join_aggregate(mesh: Mesh, n_partial: int, specs: list[str]):
    """The flagship star-schema step as ONE SPMD program.

    Sharded fact side (probe), replicated dimension side (build, unique
    keys assumed — PK side), filter mask applied, inner-join semantics,
    grouped partial aggregation by one or more dimension attributes,
    psum-free all_gather merge. This is the TPU-native shape of NDS
    power-run queries (fact x dims -> group -> agg; e.g. reference query
    templates joining store_sales to date_dim/item, SURVEY.md §0).

    specs: per-value "sum"|"count"|"min"|"max".
    Returned jittable fn:
      (fact_key, fact_mask, fact_alive, fact_values,
       dim_key, dim_group [one array or a list — composite GROUP BY],
       dim_alive) ->
      (group_keys, agg_values, out_alive, overflow) replicated; overflow
      counts rows in groups beyond n_partial (must be 0 for exact results).
    """
    axis = mesh.axis_names[0]

    def local(fact_key, fact_mask, fact_alive, fact_values,
              dim_key, dim_group, dim_alive):
        groups = _as_key_list(dim_group)
        single = not isinstance(dim_group, (list, tuple))
        alive = fact_alive & fact_mask
        # build: sort replicated dim keys once (same on every shard)
        rcap = dim_key.shape[0]
        bkey = jnp.where(dim_alive, dim_key, jnp.iinfo(fact_key.dtype).max)
        sorted_key, perm = lax.sort((bkey, jnp.arange(rcap, dtype=_I32)),
                                    num_keys=1, is_stable=True)
        idx = jnp.searchsorted(sorted_key, fact_key)
        idx = jnp.clip(idx, 0, rcap - 1)
        matched = (sorted_key[idx] == fact_key) & alive
        grps = [g[perm[idx]] for g in groups]
        gid, _ = kernels.dense_rank(grps, [matched] * len(grps), matched)
        cap = matched.shape[0]
        overflow = jnp.sum((matched & (gid >= n_partial) & (gid < cap))
                           .astype(_I32))
        reps, rep_alive = [], None
        for grp in grps:
            r, ra = kernels.group_representatives(gid, matched, grp,
                                                  matched, n_partial)
            reps.append(r)
            rep_alive = ra if rep_alive is None else rep_alive
        partials = [_partial_agg(spec, v, matched, gid, n_partial)
                    for spec, v in zip(specs, fact_values)]
        g_keys = [lax.all_gather(r, axis, tiled=True) for r in reps]
        g_alive = lax.all_gather(rep_alive, axis, tiled=True)
        g_partials = [lax.all_gather(p, axis, tiled=True) for p in partials]
        m_gid, _ = kernels.dense_rank(g_keys, [g_alive] * len(g_keys),
                                      g_alive)
        cap_out = g_keys[0].shape[0]
        out_keys, out_alive = [], None
        for gk in g_keys:
            ok, oa = kernels.group_representatives(m_gid, g_alive, gk,
                                                   g_alive, cap_out)
            out_keys.append(ok)
            out_alive = oa
        merged = [_merge_agg(spec, p, g_alive, m_gid, cap_out)
                  for spec, p in zip(specs, g_partials)]
        keys_out = out_keys[0] if single else out_keys
        return keys_out, merged, out_alive, lax.psum(overflow, axis)

    return shard_map(local, mesh=mesh,
                     in_specs=(P(axis), P(axis), P(axis), P(axis),
                               P(), P(), P()),
                     out_specs=(P(), P(), P(), P()), check_vma=False)
