"""Warehouse rollback: time-travel fact tables to a pre-maintenance state.

Capability parity with the reference rollback tool (reference
nds/nds_rollback.py:36-55: Iceberg ``rollback_to_timestamp`` over the fact
tables the maintenance test modifies, so Throughput/Maintenance test pairs
can re-run against identical data).
"""
from __future__ import annotations

import argparse
import sys

from .warehouse import Warehouse

# fact tables touched by LF_*/DF_* (reference :36-43 + DF_I's inventory)
ROLLBACK_TABLES = [
    "store_sales", "store_returns", "catalog_sales", "catalog_returns",
    "web_sales", "web_returns", "inventory",
]


def rollback(warehouse_path: str, timestamp_ms: int,
             tables: list[str] | None = None) -> None:
    wh = Warehouse(warehouse_path)
    for name in tables or ROLLBACK_TABLES:
        wt = wh.table(name)
        if wt.exists():
            snap = wt.rollback_to_timestamp(timestamp_ms)
            print(f"{name}: rolled back to snapshot state at <= "
                  f"{timestamp_ms} (new version {snap['version']})")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="nds_tpu.rollback")
    p.add_argument("warehouse_path")
    p.add_argument("timestamp_ms", type=int)
    p.add_argument("--tables", default=None)
    a = p.parse_args(argv)
    rollback(a.warehouse_path, a.timestamp_ms,
             a.tables.split(",") if a.tables else None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
