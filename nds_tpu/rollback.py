"""Warehouse rollback: time-travel fact tables to a pre-maintenance state.

Capability parity with the reference rollback tool (reference
nds/nds_rollback.py:36-55: Iceberg ``rollback_to_timestamp`` over the fact
tables the maintenance test modifies, so Throughput/Maintenance test pairs
can re-run against identical data).

Two modes:

- **per-table timestamp** (reference parity, the original mode): each
  fact table independently restores its latest snapshot at or before
  the given timestamp;
- **warehouse version** (``--version`` / ``--list``, over the
  ``_snapshots`` log): every table restores to its manifest version
  under ONE published warehouse version, committed atomically — the
  whole warehouse lands on a single consistent cut, never a blend.
"""
from __future__ import annotations

import argparse
import sys

from .warehouse import Warehouse

# fact tables touched by LF_*/DF_* (reference :36-43 + DF_I's inventory)
ROLLBACK_TABLES = [
    "store_sales", "store_returns", "catalog_sales", "catalog_returns",
    "web_sales", "web_returns", "inventory",
]


def rollback(warehouse_path: str, timestamp_ms: int,
             tables: list[str] | None = None) -> None:
    wh = Warehouse(warehouse_path)
    for name in tables or ROLLBACK_TABLES:
        wt = wh.table(name)
        if wt.exists():
            snap = wt.rollback_to_timestamp(timestamp_ms)
            print(f"{name}: rolled back to snapshot state at <= "
                  f"{timestamp_ms} (new version {snap['version']})")


def rollback_version(warehouse_path: str, version: int) -> None:
    """Atomic warehouse-level rollback to a published version."""
    wh = Warehouse(warehouse_path)
    new = wh.rollback_to_version(version)
    print(f"warehouse: rolled back to version {version} "
          f"(published as version {new})")


def list_versions(warehouse_path: str) -> None:
    wh = Warehouse(warehouse_path)
    records = wh.snapshot_records()
    if not records:
        print("no warehouse snapshot log (no transaction committed yet)")
        return
    cur = wh.current_version()
    for rec in records:
        mark = "*" if rec["version"] == cur else " "
        tables = ",".join(f"{t}@{v}"
                          for t, v in sorted(rec["tables"].items()))
        print(f"{mark} v{rec['version']} ts={rec['timestamp_ms']} "
              f"committer={rec.get('committer') or '-'} {tables}")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="nds_tpu.rollback")
    p.add_argument("warehouse_path")
    p.add_argument("timestamp_ms", type=int, nargs="?", default=None,
                   help="per-table timestamp rollback (reference parity)")
    p.add_argument("--tables", default=None)
    p.add_argument("--version", type=int, default=None,
                   help="atomic warehouse-level rollback to a published "
                        "snapshot-log version")
    p.add_argument("--list", action="store_true",
                   help="list published warehouse versions and exit")
    a = p.parse_args(argv)
    if a.list:
        list_versions(a.warehouse_path)
        return 0
    if a.version is not None:
        rollback_version(a.warehouse_path, a.version)
        return 0
    if a.timestamp_ms is None:
        p.error("timestamp_ms required (or use --version / --list)")
    rollback(a.warehouse_path, a.timestamp_ms,
             a.tables.split(",") if a.tables else None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
