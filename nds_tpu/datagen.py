"""Data-generation CLI: drives the native ndsdgen generator.

Capability parity with the reference data-gen front-end
(reference nds/nds_gen_data.py): local process-parallel generation
(generate_data_local :183-244 forks one dsdgen per chunk), per-table output
directories, incremental --range generation (:155-174), --update refresh
sets (:220-229 in nds_bench.py), and the delete-date table placement
(move_delete_date_tables :119-127). The cluster path is a host-list fanout
instead of a Hadoop MR job (SURVEY.md §2 parallelism table).
"""
from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys

SOURCE_TABLES = [
    "call_center", "catalog_page", "catalog_returns", "catalog_sales",
    "customer", "customer_address", "customer_demographics", "date_dim",
    "dbgen_version", "household_demographics", "income_band", "inventory",
    "item", "promotion", "reason", "ship_mode", "store", "store_returns",
    "store_sales", "time_dim", "warehouse", "web_page", "web_returns",
    "web_sales", "web_site",
]
MAINTENANCE_TABLES = [
    "s_purchase_lineitem", "s_purchase", "s_catalog_order", "s_web_order",
    "s_catalog_order_lineitem", "s_web_order_lineitem", "s_store_returns",
    "s_catalog_returns", "s_web_returns", "s_inventory", "delete",
    "inventory_delete",
]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BINARY = os.path.join(_REPO_ROOT, "native", "bin", "ndsdgen")


def check_build(binary: str = DEFAULT_BINARY) -> str:
    """Locate the native generator, building it if the tree is present
    (reference check.py:47-66 checks the jar/dsdgen build)."""
    if os.path.exists(binary):
        return binary
    src_dir = os.path.join(_REPO_ROOT, "native", "datagen")
    if os.path.isdir(src_dir):
        subprocess.run(["make"], cwd=src_dir, check=True,
                       capture_output=True)
        if os.path.exists(binary):
            return binary
    raise FileNotFoundError(
        f"ndsdgen binary not found at {binary}; run `make` in native/datagen")


def valid_range(r: str, parallel: int) -> tuple[int, int]:
    """Parse --range 'first,last' (1-based chunk indexes, reference
    check.py:88-123)."""
    try:
        first, last = (int(x) for x in r.split(","))
    except ValueError:
        raise ValueError(f"bad range {r!r}: expected 'first,last'")
    if not (1 <= first <= last <= parallel):
        raise ValueError(f"range {r!r} outside 1..{parallel}")
    return first, last


def generate_data_local(data_dir: str, scale: float, parallel: int,
                        chunk_range: tuple[int, int] | None = None,
                        update: int = 0,
                        binary: str | None = None,
                        overwrite: bool = False) -> None:
    """Fork one generator process per chunk and lay out per-table dirs."""
    binary = binary or check_build()
    first, last = chunk_range if chunk_range else (1, parallel)
    if chunk_range is None:
        if os.path.exists(data_dir):
            if not overwrite and os.listdir(data_dir):
                raise FileExistsError(
                    f"{data_dir} is not empty; pass overwrite to replace")
            shutil.rmtree(data_dir, ignore_errors=True)
        work = os.path.join(data_dir, "_raw_")
    else:
        # incremental range runs append into a shared data_dir (possibly
        # concurrently from several hosts): never wipe it, and keep a
        # range-private work dir so parallel runs don't race on cleanup
        work = os.path.join(data_dir, f"_raw_{first}_{last}_")
    os.makedirs(work, exist_ok=True)
    procs = []
    for child in range(first, last + 1):
        cmd = [binary, "-scale", str(scale), "-dir", work,
               "-parallel", str(parallel), "-child", str(child)]
        if update:
            cmd += ["-update", str(update)]
        procs.append((child, subprocess.Popen(cmd)))
    failed = [c for c, p in procs if p.wait() != 0]
    if failed:
        raise RuntimeError(f"generator chunks failed: {failed}")

    tables = MAINTENANCE_TABLES if update else SOURCE_TABLES
    for table in tables:
        tdir = os.path.join(data_dir, table)
        os.makedirs(tdir, exist_ok=True)
        if parallel > 1:
            for child in range(first, last + 1):
                src = os.path.join(work, f"{table}_{child}_{parallel}.dat")
                # small tables leave some chunks empty; don't ship those
                if os.path.exists(src) and os.path.getsize(src) > 0:
                    os.rename(src, os.path.join(tdir, os.path.basename(src)))
        else:
            src = os.path.join(work, f"{table}.dat")
            if os.path.exists(src):
                os.rename(src, os.path.join(tdir, f"{table}.dat"))
    shutil.rmtree(work, ignore_errors=True)

    # verify non-empty output (reference nds_gen_data.py:199-206); a range
    # subset legitimately leaves small single-chunk tables to other ranges,
    # so full verification only applies to whole runs
    if chunk_range is None:
        for table in tables:
            tdir = os.path.join(data_dir, table)
            if not os.listdir(tdir):
                raise RuntimeError(f"no output produced for table {table}")
    elif not any(os.listdir(os.path.join(data_dir, t)) for t in tables
                 if os.path.isdir(os.path.join(data_dir, t))):
        raise RuntimeError(
            f"range {first},{last} produced no output for any table")


def generate_data_hosts(data_dir: str, scale: float, parallel: int,
                        hosts: list[str], update: int = 0,
                        overwrite: bool = False) -> None:
    """Multi-host fanout: assign chunk ranges to hosts via ssh.

    The TPU-native replacement for the reference's Hadoop MR wrapper
    (GenTable.java): no cluster framework, one ssh per host with a chunk
    range; hosts share a filesystem or sync afterwards. The coordinator
    prepares the shared dir ONCE (range runs never wipe it — a stale dir
    mixed with new chunks would duplicate rows downstream).
    """
    if os.path.exists(data_dir) and os.listdir(data_dir):
        if not overwrite:
            raise FileExistsError(
                f"{data_dir} is not empty; pass overwrite to replace")
        shutil.rmtree(data_dir, ignore_errors=True)
    os.makedirs(data_dir, exist_ok=True)
    n = len(hosts)
    procs = []
    for i, host in enumerate(hosts):
        first = parallel * i // n + 1
        last = parallel * (i + 1) // n
        if first > last:
            continue
        # NOTE: no --overwrite — range runs append into the shared dir; a
        # wipe here would race the other hosts' output away
        sub = (f"python -m nds_tpu.datagen local {data_dir} --scale {scale} "
               f"--parallel {parallel} --range {first},{last}")
        if update:
            sub += f" --update {update}"
        procs.append(subprocess.Popen(["ssh", host, sub]))
    failed = [p.args for p in procs if p.wait() != 0]
    if failed:
        raise RuntimeError(f"host generation failed: {failed}")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="nds_tpu.datagen",
        description="Generate NDS benchmark data with the native generator")
    p.add_argument("mode", choices=["local", "hosts"],
                   help="local: fork processes; hosts: ssh fanout")
    p.add_argument("data_dir")
    p.add_argument("--scale", type=float, default=1.0)
    p.add_argument("--parallel", type=int, default=os.cpu_count() or 1)
    p.add_argument("--range", dest="range_", default=None,
                   help="chunk subrange 'first,last' for incremental runs")
    p.add_argument("--update", type=int, default=0,
                   help="generate refresh (maintenance) set K instead")
    p.add_argument("--overwrite", action="store_true")
    p.add_argument("--hosts", default="",
                   help="comma-separated host list for hosts mode")
    a = p.parse_args(argv)

    rng = valid_range(a.range_, a.parallel) if a.range_ else None
    if a.mode == "local":
        generate_data_local(a.data_dir, a.scale, a.parallel, rng,
                            a.update, overwrite=a.overwrite)
    else:
        hosts = [h for h in a.hosts.split(",") if h]
        if not hosts:
            p.error("hosts mode requires --hosts")
        generate_data_hosts(a.data_dir, a.scale, a.parallel, hosts, a.update,
                            overwrite=a.overwrite)
    return 0


if __name__ == "__main__":
    sys.exit(main())
