"""The ``system`` catalog: engine introspection as ordinary SQL tables.

Every telemetry surface the stack has grown — histograms and traces
(PR 11), flight/chaos evidence (PR 12), result-cache counters (PR 13),
profiles and memory watermarks (PR 14), the durable query log (this PR)
— was reachable only through Python APIs and offline report scripts.
Production engines dogfood instead ("Accelerating Presto with GPUs"
leans on Presto's ``system.runtime`` tables; PyTond's thesis is that
pushing the analysis INTO the engine beats exporting it), so NDS-TPU
introspects itself through its own SQL path:

    SELECT tenant, wall_ms FROM system.query_log
    SELECT name, value FROM system.metrics WHERE name = 'compiles'
    SELECT le_ms, count FROM system.histograms WHERE tenant = 'dash'

Contract (pinned by tests):

- **Frozen schemas** — ``SYSTEM_SCHEMAS`` lists every table's column
  names and engine dtypes; they change only deliberately.
- **Atomic snapshots** — each provider cuts its registry under that
  registry's own lock (``METRICS.rows()``/``histograms()`` are single
  atomic cuts; the query-log ring and flight ring copy under their
  locks), so a reader racing writers never sees a torn row.
- **Host-only execution** — system statements plan against a dedicated
  catalog and run on the HOST executor over in-memory snapshots: an
  operator's ``SELECT p99 ... GROUP BY tenant`` never touches the device
  lane, the planner worker pool, or any compiled-program cache, and so
  never perturbs the workload it is measuring. ``QueryService.submit``
  routes these around admission (observability must work DURING overload
  and open circuits).

The snapshot is taken per statement — polling re-reads live state.
"""
from __future__ import annotations

import json
from typing import Callable, Optional

import pyarrow as pa

from .flight import FLIGHT
from .metrics import METRICS
from .query_log import COLUMNS as _QL_COLUMNS
from .query_log import QUERY_LOG

#: catalog prefix; a statement whose tables ALL carry it is a system
#: statement (mixing system.* with user tables is rejected — the host
#: snapshot executor must never pull warehouse-scale data)
PREFIX = "system."

_ARROW = {"int": pa.int64(), "float": pa.float64(), "str": pa.string(),
          "bool": pa.bool_()}

#: the frozen table schemas: name -> ((columns...), (engine dtypes...)).
SYSTEM_SCHEMAS: dict[str, tuple[tuple, tuple]] = {
    "system.query_log": (
        tuple(c for c, _ in _QL_COLUMNS),
        tuple(t for _, t in _QL_COLUMNS)),
    "system.metrics": (
        ("name", "kind", "value", "help"),
        ("str", "str", "float", "str")),
    "system.histograms": (
        ("name", "series", "tenant", "template", "le_ms", "count",
         "cum_count", "total_count", "sum_ms", "min_ms", "max_ms"),
        ("str", "str", "str", "str", "float", "int",
         "int", "int", "float", "float", "float")),
    "system.programs": (
        ("fingerprint", "hits", "compiles", "strikes", "volatile",
         "nojit", "decisions"),
        ("str", "int", "int", "int", "bool", "bool", "int")),
    "system.result_cache": (
        ("entry", "template", "backend", "rows", "hits", "stored_at",
         "tables", "ivm"),
        ("str", "str", "str", "int", "int", "float", "str", "bool")),
    "system.device_memory": (
        ("metric", "bytes"),
        ("str", "int")),
    "system.flight": (
        ("seq", "t_ms", "event", "label", "tenant", "reason",
         "latency_ms", "detail"),
        ("int", "float", "str", "str", "str", "str", "float", "str")),
    "system.tables": (
        ("name", "generation", "est_rows", "columns", "unique_cols"),
        ("str", "int", "int", "int", "str")),
    "system.snapshots": (
        ("version", "timestamp_ms", "committer", "tables",
         "table_count", "current", "pinned"),
        ("int", "int", "str", "str", "int", "bool", "bool")),
    "system.plan_feedback": (
        ("template", "kind", "node", "table", "rows", "sightings",
         "refreshes", "gen"),
        ("str", "str", "str", "str", "int", "int", "int", "int")),
}


def system_table_names() -> tuple:
    return tuple(SYSTEM_SCHEMAS)


def is_system_table(name: str) -> bool:
    return name.startswith(PREFIX)


def catalog_entries() -> dict:
    """{name: (names, dtypes, est_rows)} in the shape the planner's
    Catalog consumes — est_rows is a nominal constant (snapshots are
    bounded rings; no cost model depends on it)."""
    return {name: (list(cols), list(dts), 4096)
            for name, (cols, dts) in SYSTEM_SCHEMAS.items()}


def _arrow(name: str, rows: list[dict]) -> pa.Table:
    cols, dts = SYSTEM_SCHEMAS[name]
    schema = pa.schema([(c, _ARROW[t]) for c, t in zip(cols, dts)])
    return pa.Table.from_pylist(
        [{c: r.get(c) for c in cols} for r in rows], schema=schema)


# -- per-table snapshot providers (each cuts its registry atomically) -------

def _query_log_rows(session) -> list[dict]:
    return QUERY_LOG.rows()


def _metrics_rows(session) -> list[dict]:
    return [{"name": n, "kind": k, "value": float(v), "help": h}
            for n, k, v, h in METRICS.rows()]


def _histogram_rows(session) -> list[dict]:
    """Bucket-level export: one row per nonzero bucket per series (le_ms
    NULL = the +Inf overflow bucket), with the exact count/sum/min/max
    repeated per row so a single SELECT carries everything a quantile
    needs — the same snapshot quantile_from_snapshot consumes."""
    out = []
    for series, snap in METRICS.histograms().items():
        labels = snap.get("labels", {})
        cum = 0
        for le, n in snap.get("buckets", ()):
            cum += n
            out.append({
                "name": snap["name"], "series": series,
                "tenant": labels.get("tenant"),
                "template": labels.get("template"),
                "le_ms": le, "count": n, "cum_count": cum,
                "total_count": snap["count"], "sum_ms": snap["sum"],
                "min_ms": snap["min"], "max_ms": snap["max"]})
    return out


def _program_rows(session) -> list[dict]:
    from ..engine.jax_backend.executor import shared_programs_snapshot
    return shared_programs_snapshot()


def _result_cache_rows(session) -> list[dict]:
    cache = getattr(session, "result_cache", None)
    if cache is None:
        return []
    return cache.snapshot_rows()


def _device_memory_rows(session) -> list[dict]:
    from .profile import DEVICE_MEM
    rows = [{"metric": "live", "bytes": DEVICE_MEM.live},
            {"metric": "peak", "bytes": DEVICE_MEM.peak},
            {"metric": "window_peak", "bytes": DEVICE_MEM.window_peak()}]
    budget_gb = getattr(session.config, "scan_budget_gb", 0) \
        if session is not None else 0
    if budget_gb and budget_gb > 0:
        budget = int(budget_gb * (1 << 30))
        rows.append({"metric": "budget", "bytes": budget})
        rows.append({"metric": "headroom",
                     "bytes": budget - DEVICE_MEM.peak})
    return rows


_FLIGHT_FIELDS = ("seq", "t_ms", "event", "label", "tenant", "reason",
                  "latency_ms")


def _flight_rows(session) -> list[dict]:
    out = []
    for e in FLIGHT.events():
        row = {k: e.get(k) for k in _FLIGHT_FIELDS}
        extra = {k: v for k, v in e.items() if k not in _FLIGHT_FIELDS}
        row["detail"] = json.dumps(extra, sort_keys=True) if extra else None
        if row["latency_ms"] is not None:
            row["latency_ms"] = float(row["latency_ms"])
        out.append(row)
    return out


def _tables_rows(session) -> list[dict]:
    if session is None:
        return []
    with session._lock:
        names = sorted(session._schemas)
        return [{"name": n,
                 "generation": session._table_generations.get(n, 0),
                 "est_rows": session._est_rows.get(n),
                 "columns": len(session._schemas[n][0]),
                 "unique_cols": ",".join(
                     sorted(session._unique_cols.get(n, ()))) or None}
                for n in names]


def _snapshot_rows(session) -> list[dict]:
    """The attached warehouse's published version log: one row per
    atomic cross-table commit (``tables`` is the ``name@manifest-
    version`` map the version pins; ``current`` marks the published
    head, ``pinned`` the version this session's reads resolve against)."""
    wh = getattr(session, "warehouse", None) if session is not None \
        else None
    if wh is None:
        return []
    cur = wh.current_version()
    pinned = session.warehouse_version()
    return [{"version": rec["version"],
             "timestamp_ms": rec["timestamp_ms"],
             "committer": rec.get("committer") or None,
             "tables": ",".join(
                 f"{t}@{v}" for t, v in sorted(rec["tables"].items())),
             "table_count": len(rec["tables"]),
             "current": rec["version"] == cur,
             "pinned": rec["version"] == pinned}
            for rec in wh.snapshot_records()]


def _plan_feedback_rows(session) -> list[dict]:
    """The adaptive-execution feedback store's observed actuals (one row
    per fact: per-node TypeName#k maxima, per-table streamed rows, and
    per-decision schedule caps). Empty when adaptive_plans is off — no
    store exists then."""
    fb = getattr(session, "_feedback", None) if session is not None \
        else None
    if fb is None:
        return []
    return fb.snapshot_rows()


PROVIDERS: dict[str, Callable] = {
    "system.query_log": _query_log_rows,
    "system.metrics": _metrics_rows,
    "system.histograms": _histogram_rows,
    "system.programs": _program_rows,
    "system.result_cache": _result_cache_rows,
    "system.device_memory": _device_memory_rows,
    "system.flight": _flight_rows,
    "system.tables": _tables_rows,
    "system.snapshots": _snapshot_rows,
    "system.plan_feedback": _plan_feedback_rows,
}


def snapshot_arrow(name: str, session=None) -> pa.Table:
    """One system table's current state as in-memory Arrow (the frozen
    schema, rows cut atomically from the owning registry)."""
    if name not in SYSTEM_SCHEMAS:
        raise KeyError(f"unknown system table {name!r} "
                       f"(have: {', '.join(SYSTEM_SCHEMAS)})")
    return _arrow(name, PROVIDERS[name](session))


def snapshot_engine_table(name: str, session=None):
    """Engine-Table view of :func:`snapshot_arrow` (the host executor's
    scan input)."""
    from ..engine import arrow_bridge
    return arrow_bridge.from_arrow(snapshot_arrow(name, session))


def collect_table_refs(ast) -> set:
    """Every table name referenced anywhere in a parsed statement
    (FROM refs under subqueries/CTEs included) — the routing decision
    input: all-system -> host introspection path, none -> normal path,
    mixed -> typed error."""
    from ..sql import ast_nodes as A
    names: set = set()
    ctes: set = set()
    seen: set = set()

    def walk(x):
        if id(x) in seen or x is None:
            return
        seen.add(id(x))
        if isinstance(x, A.TableRef):
            names.add(x.name)
        if isinstance(x, A.Query):
            ctes.update(n for n, _q in x.ctes)
        if isinstance(x, (list, tuple)):
            for item in x:
                walk(item)
            return
        if hasattr(x, "__dict__"):
            for v in vars(x).values():
                walk(v)
        elif hasattr(x, "__slots__"):
            for s in x.__slots__:
                walk(getattr(x, s, None))
    walk(ast)
    return names - ctes        # CTE aliases are not catalog tables
