"""Flight recorder: a bounded ring of query-lifecycle events.

Metrics answer "how much"; traces answer "where did the time go" for runs
you thought to trace. The flight recorder answers the post-mortem
question — *what was the service doing right before it went wrong* —
without requiring anything to be enabled ahead of the failure window
being interesting: it is cheap enough to leave on for whole service runs
(a dict append into a fixed-size ring), keeps only the most recent
``capacity`` events, and dumps itself to JSONL when something trips it:

- **explicitly** (``FLIGHT.dump_jsonl(path)`` / ``scripts/obs_report.py``),
- **on a typed-rejection storm** — ``reject_storm`` rejections inside
  ``reject_window_s`` seconds auto-dump once per cooldown, so the record
  of the overload's onset survives the overload;
- **when a FaultRegistry point fires** — chaos runs (``nds_tpu/chaos``)
  arm ``device.put``/``jax.compile``/... specs mid-service and assert
  against the dumped artifact: the ring holds the admissions, dispatches,
  and batch compositions that surrounded the injected failure;
- **when a circuit breaker trips** — a per-error-class failure storm
  crossing its windowed rate dumps the window that tripped it
  (``resilience.CircuitBreaker``), once per class per cooldown.

Events are flat dicts: ``seq`` (total-order sequence number), ``t_ms``
(monotonic ms since recorder start — immune to wall-clock steps), an
``event`` tag (admit / plan / dispatch / batch / retry / fault / reject /
expire / complete / error / trip / probe / quarantine / lifecycle_phase /
maintenance), and whatever fields the recording site attaches (label,
tenant, template, latency_ms, ...). The self-healing vocabulary: ``trip``
marks a breaker/watchdog/fault-storm moment (reason field), ``probe`` a
half-open breaker admission or its closing outcome, ``quarantine`` a
shared compiled program evicted after repeated strikes, and
``lifecycle_phase``/``maintenance`` the scored-lifecycle runner's phase
transitions interleaving with live service traffic. The transactional
vocabulary (``nds_tpu/warehouse``): ``txn_commit`` an atomic cross-table
warehouse commit landing (committer, published version, tables touched),
``txn_rollback`` a transaction aborting back to its base snapshot
(``clean`` records whether the intent record was retired or left for
recovery), and ``txn_recover`` a reopened warehouse discarding a dead
writer's orphaned partial commit. The adaptive-execution vocabulary
(``engine/feedback.py``): ``feedback_hit`` a streamed group's capacity
schedule right-sized from observed actuals, ``feedback_refresh`` the
drift sentinel replacing a stale profile, and ``adaptive_replan`` a
feedback-driven re-record (moved profile generation, or an adapted
schedule overflowed by an under-observed actual).

Disabled (the default outside the service) a record() is one attribute
read — the same near-zero contract as the span tracer. Enable with
``FLIGHT.configure(enabled=True, dump_dir=...)``, ``NDS_TPU_FLIGHT=1``
(+ ``NDS_TPU_FLIGHT_DIR``), or ``QueryService`` knobs.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional


class FlightRecorder:
    """Process-wide lifecycle-event ring (one instance: ``FLIGHT``)."""

    def __init__(self, capacity: int = 4096):
        self.enabled = False
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self._epoch = time.monotonic()
        self.dump_dir: Optional[str] = None
        #: reject-storm trip wire: N rejects inside the window auto-dump
        self.reject_storm = 50
        self.reject_window_s = 10.0
        self._rejects: deque = deque()
        #: per-reason cooldown so a sustained storm/fault burst produces
        #: one artifact per window, not one per event
        self.trip_cooldown_s = 30.0
        self._last_trip: dict[str, float] = {}
        #: paths written by automatic trips (inspection/tests), oldest
        #: first — the retention caps below evict from the FRONT
        self.dumps: list[str] = []
        #: dump retention (a reject-storm or long chaos campaign must not
        #: grow the dump dir unboundedly): most dump files kept, and a
        #: total-bytes cap across them — oldest-first eviction, applied
        #: only to files THIS recorder wrote (self.dumps)
        self.max_dumps = 200
        self.max_dump_bytes = 256 << 20
        self._dump_bytes: dict[str, int] = {}
        #: monotonic dump index: filenames sort chronologically and stay
        #: stable under wall-clock steps (seq-stable naming)
        self._dump_seq = 0

    # -- control -------------------------------------------------------------
    def configure(self, enabled: bool = True,
                  capacity: Optional[int] = None,
                  dump_dir: Optional[str] = None,
                  reject_storm: Optional[int] = None,
                  reject_window_s: Optional[float] = None,
                  trip_cooldown_s: Optional[float] = None,
                  max_dumps: Optional[int] = None,
                  max_dump_bytes: Optional[int] = None,
                  clear: bool = True) -> "FlightRecorder":
        """``trip_cooldown_s`` 0 dumps on EVERY trip — chaos campaigns
        set it so an artifact exists per firing (the default 30s keeps a
        sustained production storm to one dump per window per reason).
        ``max_dumps``/``max_dump_bytes`` cap automatic-trip dump
        retention: past either cap the OLDEST dump files this recorder
        wrote are deleted first (a long campaign keeps its newest
        evidence; the dir stays bounded)."""
        with self._lock:
            if capacity is not None:
                self._ring = deque(self._ring, maxlen=capacity)
            if dump_dir is not None:
                self.dump_dir = dump_dir
            if reject_storm is not None:
                self.reject_storm = reject_storm
            if reject_window_s is not None:
                self.reject_window_s = reject_window_s
            if trip_cooldown_s is not None:
                self.trip_cooldown_s = trip_cooldown_s
            if max_dumps is not None:
                self.max_dumps = max_dumps
            if max_dump_bytes is not None:
                self.max_dump_bytes = max_dump_bytes
            if clear:
                self._ring.clear()
                self._rejects.clear()
                self._last_trip.clear()
                self.dumps = []
                self._dump_bytes = {}
                self._dump_seq = 0
                self._seq = 0
                self._epoch = time.monotonic()
            self.enabled = enabled
        return self

    def clear(self) -> None:
        self.configure(enabled=self.enabled, clear=True)

    # -- recording -----------------------------------------------------------
    def record(self, event: str, **fields) -> None:
        """Append one lifecycle event (no-op while disabled). A "reject"
        event also feeds the storm trip wire."""
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            self._seq += 1
            e = {"seq": self._seq,
                 "t_ms": round((now - self._epoch) * 1000.0, 3),
                 "event": event}
            e.update(fields)
            self._ring.append(e)
            if event != "reject":
                return
            self._rejects.append(now)
            while self._rejects and \
                    now - self._rejects[0] > self.reject_window_s:
                self._rejects.popleft()
            storm = len(self._rejects) >= self.reject_storm
            count = len(self._rejects)
            if storm:
                # one trip per storm: the next trip needs a fresh window
                # of rejections (the dump cooldown additionally bounds
                # artifact volume under sustained overload)
                self._rejects.clear()
        if storm:
            self.trip("reject_storm", rejects=count,
                      window_s=self.reject_window_s)

    def trip(self, reason: str, **fields) -> Optional[str]:
        """Something post-mortem-worthy happened: record a "trip" event
        and, when a dump_dir is configured, write the ring to a JSONL
        artifact (rate-limited per reason by trip_cooldown_s). Returns
        the written path, or None when rate-limited / not dumping."""
        if not self.enabled:
            return None
        now = time.monotonic()
        with self._lock:
            last = self._last_trip.get(reason)
            limited = last is not None and \
                now - last < self.trip_cooldown_s
            if not limited:
                self._last_trip[reason] = now
        self.record("trip", reason=reason, dumped=not limited, **fields)
        if limited or not self.dump_dir:
            return None
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)   # "circuit:FaultError" etc.
        with self._lock:
            # monotonic, seq-stable filename: sorting a dump dir by name
            # is chronological regardless of wall-clock steps, and two
            # trips inside one second never collide
            self._dump_seq += 1
            path = os.path.join(
                self.dump_dir,
                f"flight_{self._dump_seq:05d}_{safe}.jsonl")
        self.dump_jsonl(path)
        with self._lock:
            self.dumps.append(path)
            try:
                self._dump_bytes[path] = os.path.getsize(path)
            except OSError:
                self._dump_bytes[path] = 0
            evict = self._retention_evict_locked()
        for old in evict:
            try:
                os.remove(old)
            except OSError:
                pass
        return path

    def _retention_evict_locked(self) -> list[str]:
        """Oldest-first eviction past max_dumps/max_dump_bytes: returns
        the paths to delete (removed from the bookkeeping here, unlinked
        by the caller outside the lock). Only files this recorder wrote
        are ever candidates."""
        evict: list[str] = []
        total = sum(self._dump_bytes.values())
        while self.dumps and (
                len(self.dumps) > self.max_dumps
                or (total > self.max_dump_bytes and len(self.dumps) > 1)):
            old = self.dumps.pop(0)
            total -= self._dump_bytes.pop(old, 0)
            evict.append(old)
        return evict

    # -- inspection / export -------------------------------------------------
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def dump_jsonl(self, path: str) -> str:
        """Write the current ring, oldest first, one event per line —
        the artifact ``scripts/trace_report.py`` / ``obs_report.py``
        summarize and chaos runs assert against."""
        events = self.events()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        return path


#: the process-global recorder every lifecycle hook reports into.
FLIGHT = FlightRecorder()

if os.environ.get("NDS_TPU_FLIGHT", "").lower() in ("1", "true", "yes",
                                                    "on"):
    FLIGHT.configure(enabled=True,
                     dump_dir=os.environ.get("NDS_TPU_FLIGHT_DIR") or ".")
