"""Span tracer: the query lifecycle as a tree of timed spans.

The engine's remaining orders of magnitude hide inside phases no single
number names: `roofline_frac` says the chip is 0.35% busy but not which
operator of which query burns the time. Interactive engines treat
per-operator runtime stats as the foundation of every optimization
decision ("Accelerating Presto with GPUs", PAPERS.md); Flare instruments
at the compiled-program boundary, not the interpreter loop ("Flare",
PAPERS.md). This tracer does both: parse -> plan (per rewrite pass, incl.
verification) -> compile -> lane-pack/upload -> per-morsel device exec ->
merge/finalize, each a span with parent/child structure and attributes
(rows, bytes, table, plan fingerprint).

Design constraints, in order:

1. **Near-zero cost disabled.** Every hook is `TRACER.span(...)`; when
   disabled that is one attribute read plus returning a shared no-op
   context manager — no allocation, no lock, no clock read. The engine is
   instrumented unconditionally and pays nothing in production
   (acceptance: <2% bench-slice overhead with tracing off).
2. **Thread-safe.** The staging thread, deadline workers, and parallel
   compile pools all open spans; the parent stack is thread-local and the
   event sink is lock-protected.
3. **Standard export formats.** Chrome trace-event JSON (opens directly
   in Perfetto / chrome://tracing), JSONL event logs for ad-hoc grep, and
   an aggregated per-name table embedded in bench reports.

Enable per-process with ``configure(enabled=True)`` (runners expose
``--trace``) or by exporting ``NDS_TPU_TRACE=1``.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Optional


class _NullSpan:
    """Shared do-nothing span returned while tracing is disabled."""
    __slots__ = ()
    sid = 0     # detached-span protocol: a disabled span has no identity

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self

    def begin(self) -> "_NullSpan":
        return self

    def end(self, error: Optional[str] = None) -> None:
        return None


NULL_SPAN = _NullSpan()


class Span:
    """One live span; becomes an event dict when closed.

    Event layout is the Chrome trace-event "complete" form (ph="X", ts/dur
    in microseconds) extended with ``sid``/``parent`` so the span tree is
    reconstructible from the flat event list (Perfetto ignores the extra
    keys).

    Two lifetimes: the context-manager form nests via the thread-local
    parent stack (same-thread children), and the DETACHED form
    (``begin()``/``end()``) lives across thread hops — a service ticket's
    root span opens on the client thread at admission and closes on the
    device lane at completion, with every stage span parent-linked to it
    through the explicit ``parent=`` override."""
    __slots__ = ("name", "cat", "attrs", "sid", "parent", "tid", "_t0",
                 "_tracer", "_parent_override", "_detached")

    def __init__(self, tracer: "Tracer", name: str, cat: str, attrs: dict,
                 parent: Optional[int] = None):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.sid = 0
        self.parent = 0
        self.tid = 0
        self._t0 = 0.0
        self._parent_override = parent
        self._detached = False

    def set(self, **attrs) -> "Span":
        """Attach attributes discovered mid-span (rows, bytes, mode...)."""
        self.attrs.update(attrs)
        return self

    def _open(self) -> None:
        tr = self._tracer
        self.sid = next(tr._ids)
        self.tid = threading.get_ident()
        with tr._lock:
            tr._open[self.sid] = self
        self._t0 = time.perf_counter()

    def __enter__(self) -> "Span":
        tr = self._tracer
        stack = tr._stack()
        self.parent = self._parent_override if self._parent_override \
            is not None else (stack[-1] if stack else 0)
        self._open()
        stack.append(self.sid)
        return self

    def begin(self) -> "Span":
        """Open DETACHED: not pushed on any thread's parent stack, so it
        may be closed (``end()``) from a different thread. Parent comes
        only from the explicit ``parent=`` override (0 = root)."""
        self._detached = True
        self.parent = self._parent_override or 0
        self._open()
        return self

    def end(self, error: Optional[str] = None) -> None:
        """Close a detached span (thread-agnostic counterpart of
        ``__exit__``)."""
        self._close(error)

    def _close(self, error: Optional[str]) -> None:
        t1 = time.perf_counter()
        tr = self._tracer
        if error is not None:
            self.attrs["error"] = error
        event = {
            "name": self.name, "cat": self.cat, "ph": "X",
            "ts": round((self._t0 - tr._epoch) * 1e6, 1),
            "dur": round((t1 - self._t0) * 1e6, 1),
            "pid": os.getpid(), "tid": self.tid,
            "sid": self.sid, "parent": self.parent,
        }
        if self.attrs:
            event["args"] = self.attrs
        with tr._lock:
            tr._open.pop(self.sid, None)
            tr._events.append(event)

    def __exit__(self, exc_type, exc, tb) -> bool:
        stack = self._tracer._stack()
        if stack and stack[-1] == self.sid:
            stack.pop()
        self._close(exc_type.__name__ if exc_type is not None else None)
        return False


class Tracer:
    """Process-wide span collector (one instance: ``TRACER``)."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._open: dict[int, Span] = {}
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._epoch = time.perf_counter()

    # -- recording -----------------------------------------------------------
    def span(self, name: str, cat: str = "engine",
             parent: Optional[int] = None, **attrs):
        """Open a span; use as a context manager. The ONLY hook call sites
        need — a plain no-op while disabled.

        ``parent``: explicit parent span id, overriding the thread-local
        stack — how the query service parent-links a ticket's stage spans
        (planner thread, device lane, client materialization) back to the
        ``service/ticket`` root opened on the submitting thread. Use
        ``.begin()``/``.end()`` instead of ``with`` for a span that opens
        and closes on different threads."""
        if not self.enabled:
            return NULL_SPAN
        return Span(self, name, cat, attrs, parent=parent)

    def instant(self, name: str, cat: str = "engine", **attrs) -> None:
        """Record a zero-duration marker event (ph="i")."""
        if not self.enabled:
            return
        event = {"name": name, "cat": cat, "ph": "i", "s": "t",
                 "ts": round((time.perf_counter() - self._epoch) * 1e6, 1),
                 "pid": os.getpid(), "tid": threading.get_ident()}
        if attrs:
            event["args"] = attrs
        with self._lock:
            self._events.append(event)

    def _stack(self) -> list[int]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    # -- control -------------------------------------------------------------
    def configure(self, enabled: bool = True, clear: bool = True) -> None:
        if clear:
            self.clear()
        self.enabled = enabled

    def clear(self) -> None:
        with self._lock:
            self._events = []
            self._open = {}
        self._epoch = time.perf_counter()

    # -- inspection ----------------------------------------------------------
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def open_spans(self) -> list[str]:
        """Names of spans entered but not yet exited (well-formedness:
        empty at every quiescent point)."""
        with self._lock:
            return [s.name for s in self._open.values()]

    def aggregate(self) -> dict[str, dict]:
        """Per-span-name rollup: {name: {count, total_ms, max_ms}} — the
        compact per-query table bench reports embed."""
        out: dict[str, dict] = {}
        for e in self.events():
            if e.get("ph") != "X":
                continue
            row = out.setdefault(e["name"],
                                 {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
            ms = e["dur"] / 1000.0
            row["count"] += 1
            row["total_ms"] = round(row["total_ms"] + ms, 3)
            row["max_ms"] = round(max(row["max_ms"], ms), 3)
        return out

    # -- export --------------------------------------------------------------
    def write_chrome_trace(self, path: str) -> str:
        """Chrome trace-event JSON: open the file in Perfetto
        (ui.perfetto.dev) or chrome://tracing."""
        payload = {"traceEvents": self.events(), "displayTimeUnit": "ms"}
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f)
        return path

    def write_jsonl(self, path: str) -> str:
        """One event per line — greppable / streamable log form."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            for e in self.events():
                f.write(json.dumps(e) + "\n")
        return path


#: the process-global tracer every engine hook reports into.
TRACER = Tracer()

if os.environ.get("NDS_TPU_TRACE", "").lower() in ("1", "true", "yes", "on"):
    TRACER.configure(enabled=True)


def span(name: str, cat: str = "engine", **attrs):
    """Module-level convenience: ``with obs.trace.span("parse"): ...``"""
    return TRACER.span(name, cat, **attrs)


def validate_chrome_trace(path: str) -> int:
    """Structural check of an exported Chrome trace file; returns the event
    count, raising ValueError on malformed content (test + CLI helper)."""
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        raise ValueError("traceEvents is not a list")
    for e in events:
        for k in ("name", "ph", "ts", "pid", "tid"):
            if k not in e:
                raise ValueError(f"event missing {k!r}: {e}")
        if e["ph"] == "X" and "dur" not in e:
            raise ValueError(f"complete event missing dur: {e}")
    return len(events)


def span_tree(events: list[dict]) -> dict[int, list[int]]:
    """parent sid -> [child sids] from an event list (0 = roots). Raises
    ValueError when a non-root parent id never appears as a span — the
    well-formedness test's backbone."""
    sids = {e["sid"] for e in events if e.get("ph") == "X"}
    tree: dict[int, list[int]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        parent = e.get("parent", 0)
        if parent and parent not in sids:
            raise ValueError(f"span {e['sid']} ({e['name']}) has unknown "
                             f"parent {parent}")
        tree.setdefault(parent, []).append(e["sid"])
    return tree
