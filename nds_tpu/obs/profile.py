"""EXPLAIN ANALYZE: per-plan-node runtime profiles + device-memory watermarks.

PR 6 attributes device time to whole compiled programs and the service
layer keeps per-tenant latency histograms — but when a template regresses
nothing could say *which plan operator* is responsible, whether the
planner's static size assumptions matched reality, or how close a query
came to the device-memory ceiling. This module is that missing layer
(the per-operator profiling discipline "Accelerating Presto with GPUs"
and Flare treat as table stakes, PAPERS.md):

- :func:`plan_tree` — stable per-plan-node identities: the SAME
  ``TypeName#k`` preorder labels ``engine/verify.py`` anchors findings to
  (``node_labels``), so profiles, verifier findings, and
  ``ExecStats.node_stats`` all name the same node;
- :class:`PlanProfile` / :class:`NodeStat` — the profile artifact one
  profiled execution produces (``Session.explain_analyze`` /
  ``EngineConfig.profile_plans``): per node wall/rows/bytes, estimate
  beside actual, serializable (``to_dict``/``from_dict``) so runners can
  embed it in JSON summaries and ``scripts/explain_report.py`` can render
  it offline;
- :func:`estimate_rows` — the planner's STATIC size assumptions re-derived
  per node (scan = catalog est_rows, join = probe-side bound, capacity =
  the ladder bucket of the estimate), the "expected" side of the audit;
- :func:`cardinality_audit` — estimate-vs-actual diff flagging
  misestimates above a ratio threshold as structured findings (with the
  capacity-ladder bucket drift that actually costs recompiles/memory);
- :func:`render_profile` — the annotated plan tree (time %, rows
  est->act, bytes, memory peak) ``power --explain`` prints;
- :data:`DEVICE_MEM` — device-memory watermark accountant threaded
  through ``device.to_device``/``pack_table``/``stage_sharded`` and the
  codebook cache: live set, process peak, and per-query window peaks
  surfaced as gauges (``device_live_bytes``/``device_peak_bytes``), in
  ``ExecStats.mem_*``, and as the ``memory`` block in bench JSON.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional


# --------------------------------------------------------------------------
# device-memory watermark accounting
# --------------------------------------------------------------------------

class DeviceMemTracker:
    """Accounting of TRACKED device allocations, not a full HBM profiler.

    Tracked: every upload through ``device.to_device`` / ``pack_table`` /
    ``shard_exec.stage_sharded`` and the device codebook cache; frees
    through ``device.free_dtable`` (and codebook-cache resets) subtract.
    NOT tracked: compiled-program intermediates and outputs — XLA owns
    those, and the engine's memory lever is the upload/scan live set this
    tracker watches (the scan-budget eviction operates on exactly it).

    Buffers are tracked by leaf-array identity, so a double add or a free
    of an untracked tree (segment outputs, device-computed tables) never
    corrupts the balance; buffers dropped to the GC without an explicit
    ``free_dtable`` stay counted until process end (documented drift —
    the engine frees every hot-loop buffer explicitly).

    ``mark_window()``/``window_peak()`` give per-query peaks: the session
    marks at statement start (under its statement lock, so windows never
    interleave) and reads the window's high-water mark into
    ``ExecStats.mem_peak_bytes`` at finish.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._leaves: dict[int, int] = {}   # id(device array) -> bytes
        self.live = 0
        self.peak = 0
        self._win_peak = 0

    def _gauges(self, live: int, peak: int) -> None:
        from . import metrics as _m
        _m.DEVICE_LIVE_BYTES.set(live)
        _m.DEVICE_PEAK_BYTES.set(peak)

    def add(self, leaves) -> None:
        """Track [(id, nbytes)] device-array leaves (untracked ids only)."""
        with self._lock:
            for i, b in leaves:
                if i not in self._leaves:
                    self._leaves[i] = b
                    self.live += b
            if self.live > self.peak:
                self.peak = self.live
            if self.live > self._win_peak:
                self._win_peak = self.live
            live, peak = self.live, self.peak
        self._gauges(live, peak)

    def free(self, leaves) -> None:
        """Untrack [(id, nbytes)] leaves; ids never tracked are ignored."""
        with self._lock:
            for i, _b in leaves:
                b = self._leaves.pop(i, None)
                if b is not None:
                    self.live -= b
            live, peak = self.live, self.peak
        self._gauges(live, peak)

    def mark_window(self) -> None:
        """Open a per-query peak window (statement start)."""
        with self._lock:
            self._win_peak = self.live

    def window_peak(self) -> int:
        """High-water mark of the live set since ``mark_window``."""
        with self._lock:
            return self._win_peak

    def restore_window(self, saved_peak: int) -> None:
        """Re-open a suspended statement's peak window (the service's
        morsel-boundary preemption nests a statement inside another):
        the resumed window's peak is the max of what the outer statement
        had already seen and everything since — the outer statement's
        mem_peak_bytes keeps covering its whole wall."""
        with self._lock:
            self._win_peak = max(saved_peak, self._win_peak)

    def reset(self) -> None:
        """Zero all accounting (tests only)."""
        with self._lock:
            self._leaves.clear()
            self.live = 0
            self.peak = 0
            self._win_peak = 0
        self._gauges(0, 0)


#: the process-global device-memory accountant (device.py writes through)
DEVICE_MEM = DeviceMemTracker()


def memory_block(budget_bytes: Optional[int] = None) -> dict:
    """The ``memory`` block runners embed in their JSON output: live set,
    process peak, and (when the HBM budget is known) headroom between the
    peak and the budget."""
    out = {"device_live_bytes": DEVICE_MEM.live,
           "device_peak_bytes": DEVICE_MEM.peak}
    if budget_bytes:
        out["budget_bytes"] = int(budget_bytes)
        out["headroom_bytes"] = int(budget_bytes) - DEVICE_MEM.peak
    return out


# --------------------------------------------------------------------------
# plan-node identities + tree structure
# --------------------------------------------------------------------------

def _subquery_plans(node) -> list:
    """Plans DIRECTLY embedded in this node's expressions
    (BScalarSubquery roots reachable without crossing another PlanNode),
    in deterministic field order — they render as extra children of the
    node whose expression consumes them."""
    import dataclasses as _dc

    from ..engine import plan as P

    out: list = []

    def rec(x):
        if isinstance(x, P.BScalarSubquery):
            out.append(x.plan)
            return
        if isinstance(x, P.PlanNode) or isinstance(x, (str, int, float,
                                                       bool)) or x is None:
            return
        if _dc.is_dataclass(x) and not isinstance(x, type):
            for f in P.type_fields(x):
                rec(getattr(x, f))
        elif isinstance(x, (list, tuple)):
            for v in x:
                rec(v)

    for f in ("predicate", "exprs", "left_keys", "right_keys", "residual",
              "group_exprs", "aggs", "funcs", "keys"):
        if hasattr(node, f):
            rec(getattr(node, f))
    return out


def plan_tree(root):
    """(labels, children, order) for a plan DAG.

    - ``labels``: ``{id(node): "TypeName#k"}`` — verify.node_labels, the
      SAME stable preorder identity verifier findings use, preserved for
      free through rewrite passes because it is a pure function of the
      final plan's structure (two structurally identical plans label
      identically, parameterization does not change node order);
    - ``children``: ``{label: [child label, ...]}`` — plan fields
      (child/left/right) first, then expression-embedded subquery roots;
    - ``order``: distinct nodes children-first (post-order) — the safe
      execution order for a node-by-node profiled walk (every child is
      memoized before its parent runs).
    """
    from ..engine import plan as P
    from ..engine.verify import node_labels

    labels = node_labels(root)
    children: dict[str, list[str]] = {}
    order: list = []
    seen: set[int] = set()

    def kids(n) -> list:
        out = []
        for f in ("child", "left", "right"):
            sub = getattr(n, f, None)
            if isinstance(sub, P.PlanNode):
                out.append(sub)
        out.extend(_subquery_plans(n))
        return out

    def visit(n):
        if id(n) in seen:
            return
        seen.add(id(n))
        ks = kids(n)
        children[labels[id(n)]] = [labels[id(k)] for k in ks]
        for k in ks:
            visit(k)
        order.append(n)

    visit(root)
    return labels, children, order


def node_detail(node) -> str:
    """Short human detail for one node: scan table, join kind, agg arity."""
    t = type(node).__name__
    if t == "ScanNode":
        return node.table
    if t == "JoinNode":
        return node.kind + ("+late_mat" if getattr(node, "late_mat", False)
                            else "")
    if t == "AggregateNode":
        return f"{len(node.group_exprs)}g/{len(node.aggs)}a" + \
            ("+rollup" if node.rollup else "")
    if t == "LimitNode":
        return str(node.n)
    if t == "SetOpNode":
        return node.op + (" all" if node.all else "")
    if t in ("MaterializedNode", "VirtualScanNode"):
        return getattr(node, "label", "") or getattr(node, "key", "")
    return ""


# --------------------------------------------------------------------------
# static row estimates (the planner's size assumptions)
# --------------------------------------------------------------------------

def estimate_rows(root, est_rows_fn: Callable[[str], Optional[int]]
                  ) -> dict[int, Optional[int]]:
    """{id(node): estimated output rows} from the planner's STATIC stats —
    the same inputs streaming thresholds, the capacity ladder, and the
    late-mat size gate consult (catalog est_rows per scan; no per-node
    selectivity model exists, so non-scan estimates are the structural
    upper bounds capacity planning actually assumes). None = unknown
    (virtual scans whose source is another compile unit)."""
    from ..engine import plan as P

    memo: dict[int, Optional[int]] = {}

    def est(n) -> Optional[int]:
        if id(n) in memo:
            return memo[id(n)]
        memo[id(n)] = None          # cycle guard (plans are DAGs, not cyclic)
        t = type(n).__name__
        out: Optional[int]
        if isinstance(n, P.ScanNode):
            out = est_rows_fn(n.table)
        elif isinstance(n, P.MaterializedNode):
            out = n.table.num_rows          # already computed: exact
        elif t == "VirtualScanNode":
            out = None
        elif isinstance(n, P.JoinNode):
            le, ri = est(n.left), est(n.right)
            if le is None or ri is None:
                out = None
            elif n.kind == "cross":
                out = le * ri
            elif n.kind in ("semi", "anti"):
                out = le
            elif n.kind == "full":
                out = le + ri
            else:       # inner/left/right: the probe-side (fact) bound
                out = max(le, ri)
        elif isinstance(n, P.SetOpNode):
            le, ri = est(n.left), est(n.right)
            if le is None or ri is None:
                out = None
            else:
                out = le + ri if n.op == "union" else le
        elif isinstance(n, P.LimitNode):
            c = est(n.child)
            out = n.n if c is None else min(n.n, c)
        else:
            c = getattr(n, "child", None)
            out = est(c) if c is not None else None
        memo[id(n)] = out
        return out

    for n in P.iter_plan_nodes(root):
        est(n)
    return memo


# --------------------------------------------------------------------------
# the profile artifact
# --------------------------------------------------------------------------

@dataclass
class NodeStat:
    """One plan node's profiled execution record."""
    label: str                      # stable TypeName#k identity
    op: str                         # node type name
    detail: str = ""                # table / join kind / agg arity
    est_rows: Optional[int] = None  # planner static estimate
    rows: Optional[int] = None      # exact actual output rows
    wall_ms: Optional[float] = None  # this node's own wall (children memoized)
    bytes: Optional[int] = None     # device bytes of the node's output
    children: list = field(default_factory=list)   # child labels

    def to_dict(self) -> dict:
        out = {"label": self.label, "op": self.op}
        for k in ("detail", "est_rows", "rows", "wall_ms", "bytes"):
            v = getattr(self, k)
            if v not in (None, ""):
                out[k] = v
        if self.children:
            out["children"] = list(self.children)
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "NodeStat":
        return cls(label=d["label"], op=d.get("op", "?"),
                   detail=d.get("detail", ""), est_rows=d.get("est_rows"),
                   rows=d.get("rows"), wall_ms=d.get("wall_ms"),
                   bytes=d.get("bytes"),
                   children=list(d.get("children", ())))


@dataclass
class PlanProfile:
    """One profiled execution: the annotated plan tree + audit + memory.

    ``nodes`` keys are the stable TypeName#k labels; ``root`` names the
    plan root. ``table`` (not serialized) holds the result Table of the
    profiled run — bit-identical to unprofiled execution by construction
    (the profiled walk runs the SAME executor eagerly; the streamed path
    runs completely unchanged and only reads counters)."""
    query: str = ""                 # label (query9, ...)
    backend: str = "jax"
    mode: str = "in-core"           # in-core | streaming | numpy
    total_ms: float = 0.0           # profiled execution wall
    root: str = ""
    nodes: dict = field(default_factory=dict)     # label -> NodeStat
    findings: list = field(default_factory=list)  # cardinality audit
    memory: dict = field(default_factory=dict)    # watermark block
    table: object = None            # result Table (not serialized)

    def profiled_ms(self) -> float:
        """Sum of per-node walls (acceptance: >= 90% of total_ms for the
        eager in-core walk — everything outside is plan/merge glue)."""
        return sum(ns.wall_ms or 0.0 for ns in self.nodes.values())

    def to_dict(self) -> dict:
        return {"profile_version": 1, "query": self.query,
                "backend": self.backend, "mode": self.mode,
                "total_ms": round(self.total_ms, 3), "root": self.root,
                "nodes": {k: v.to_dict() for k, v in self.nodes.items()},
                "findings": list(self.findings),
                "memory": dict(self.memory)}

    @classmethod
    def from_dict(cls, d: dict) -> "PlanProfile":
        return cls(query=d.get("query", ""), backend=d.get("backend", ""),
                   mode=d.get("mode", ""), total_ms=d.get("total_ms", 0.0),
                   root=d.get("root", ""),
                   nodes={k: NodeStat.from_dict(v)
                          for k, v in d.get("nodes", {}).items()},
                   findings=list(d.get("findings", ())),
                   memory=dict(d.get("memory", {})))

    def render(self, top_findings: int = 8) -> str:
        return render_profile(self, top_findings=top_findings)


# --------------------------------------------------------------------------
# the estimate-vs-actual cardinality audit
# --------------------------------------------------------------------------

def cardinality_audit(profile: PlanProfile, ratio: float = 4.0) -> list:
    """Structured misestimate findings: nodes whose actual row count
    diverges from the planner's static estimate by at least ``ratio``
    (either direction, +1-smoothed so empty outputs compare sanely).
    Each finding records whether the CAPACITY LADDER bucket drifted too —
    a misestimate inside one bucket costs nothing (same compiled shape,
    same device buffer); across buckets it is the class that recompiles
    programs and over/under-sizes device memory."""
    try:
        from ..engine.jax_backend.device import bucket as _bucket
    except Exception:               # renderer-only environments
        def _bucket(n, minimum=8):
            return n
    findings = []
    for label, ns in profile.nodes.items():
        if ns.est_rows is None or ns.rows is None:
            continue
        est, act = int(ns.est_rows), int(ns.rows)
        r = (est + 1) / (act + 1)
        if r < 1.0:
            r = 1.0 / r
        if r < ratio:
            continue
        b_est = _bucket(max(est, 1))
        b_act = _bucket(max(act, 1))
        findings.append({
            "kind": "misestimate",
            "label": label, "op": ns.op, "detail": ns.detail,
            "est_rows": est, "rows": act, "ratio": round(r, 1),
            "direction": "over" if est > act else "under",
            "bucket_est": b_est, "bucket_act": b_act,
            "bucket_drift": b_est != b_act,
        })
    findings.sort(key=lambda f: (-f["bucket_drift"], -f["ratio"]))
    return findings


# --------------------------------------------------------------------------
# renderer
# --------------------------------------------------------------------------

def _fmt_rows(n: Optional[int]) -> str:
    if n is None:
        return "-"
    if n >= 10_000_000:
        return f"{n / 1e6:.0f}M"
    if n >= 100_000:
        return f"{n / 1e3:.0f}k"
    return str(n)


def _fmt_bytes(b: Optional[int]) -> str:
    if not b:
        return "-"
    if b >= 1 << 30:
        return f"{b / (1 << 30):.2f}GB"
    if b >= 1 << 20:
        return f"{b / (1 << 20):.1f}MB"
    if b >= 1 << 10:
        return f"{b / (1 << 10):.1f}KB"
    return f"{b}B"


def render_profile(p: PlanProfile, top_findings: int = 8) -> str:
    """The annotated plan tree: one line per node with self wall + time%,
    rows est->act, output bytes; shared (DAG) subtrees print once and
    later references point back. Findings and the memory watermark block
    follow the tree."""
    total = p.total_ms or 1e-9
    flagged = {f["label"] for f in p.findings}
    lines = [f"{p.query or 'query'}  [{p.backend}/{p.mode}]  "
             f"total {p.total_ms:.1f} ms, per-node "
             f"{p.profiled_ms():.1f} ms "
             f"({100.0 * p.profiled_ms() / total:.0f}%)"]
    printed: set[str] = set()

    def line(label: str, prefix: str, tail: str) -> None:
        ns = p.nodes.get(label)
        if ns is None:
            lines.append(f"{prefix}{label} (not executed)")
            return
        name = f"{ns.op.replace('Node', '')}#{label.rsplit('#', 1)[-1]}"
        if ns.detail:
            name += f"[{ns.detail}]"
        if label in printed:
            lines.append(f"{prefix}{name} (shared, profiled above)")
            return
        printed.add(label)
        wall = ns.wall_ms or 0.0
        pct = 100.0 * wall / total
        est = _fmt_rows(ns.est_rows)
        act = _fmt_rows(ns.rows)
        flag = "  <-- misestimate" if label in flagged else ""
        lines.append(f"{prefix}{name:<{max(44 - len(prefix), 8)}} "
                     f"{wall:>9.1f}ms {pct:>5.1f}%  "
                     f"rows {est:>7}->{act:<7} {_fmt_bytes(ns.bytes):>8}"
                     f"{flag}")
        kids = ns.children
        for i, k in enumerate(kids):
            last = i == len(kids) - 1
            branch = "`-- " if last else "|-- "
            cont = "    " if last else "|   "
            line(k, tail + branch, tail + cont)

    line(p.root, "", "")
    if p.findings:
        lines.append(f"cardinality audit: {len(p.findings)} misestimate(s)"
                     " (worst first; bucket drift = recompile/memory risk)")
        for f in p.findings[:top_findings]:
            drift = (f" bucket {_fmt_rows(f['bucket_est'])}->"
                     f"{_fmt_rows(f['bucket_act'])}"
                     if f.get("bucket_drift") else "")
            det = f"[{f['detail']}]" if f.get("detail") else ""
            lines.append(
                f"  {f['label']}{det}: est "
                f"{_fmt_rows(f['est_rows'])} vs actual "
                f"{_fmt_rows(f['rows'])} ({f['ratio']}x "
                f"{f['direction']}){drift}")
    if p.memory:
        m = p.memory
        head = (f"memory: query peak {_fmt_bytes(m.get('query_peak_bytes'))}"
                f", live {_fmt_bytes(m.get('device_live_bytes'))}"
                f", process peak {_fmt_bytes(m.get('device_peak_bytes'))}")
        if m.get("budget_bytes"):
            head += (f", headroom {_fmt_bytes(m.get('headroom_bytes'))} "
                     f"of {_fmt_bytes(m.get('budget_bytes'))} budget")
        lines.append(head)
    return "\n".join(lines)
