"""Engine diagnostics logging: one ``logging``-based channel with a
verbosity flag, replacing the raw ``sys.stderr`` writes the runners grew.

Levels map to a single integer verbosity so runners expose one knob
(``NDS_TPU_VERBOSITY`` / ``--quiet`` / ``-v``):

    0 -> WARNING  (silent except problems)
    1 -> INFO     (per-query diagnostic lines; the previous behavior)
    2 -> DEBUG    (span/metric chatter)

Everything goes to **stderr**: runner stdout is a machine contract (the
bench driver parses the single JSON line; power's CSV scrapes are files),
so diagnostics must never interleave with it.
"""
from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_ROOT = "nds_tpu"
_LEVELS = {0: logging.WARNING, 1: logging.INFO, 2: logging.DEBUG}
_configured = False


def configure(verbosity: Optional[int] = None, stream=None, force: bool = False
              ) -> logging.Logger:
    """Idempotently install the stderr handler on the ``nds_tpu`` logger.

    verbosity None reads ``NDS_TPU_VERBOSITY`` (default 1: the per-query
    diagnostic lines the runners always printed keep appearing)."""
    global _configured
    root = logging.getLogger(_ROOT)
    if verbosity is None:
        try:
            verbosity = int(os.environ.get("NDS_TPU_VERBOSITY", "1"))
        except ValueError:
            verbosity = 1
    if force or not _configured:
        for h in list(root.handlers):
            root.removeHandler(h)
        handler = logging.StreamHandler(stream or sys.stderr)
        # message-only: these lines replace bare stderr writes, and scrapers
        # of old runner output must keep matching
        handler.setFormatter(logging.Formatter("%(message)s"))
        root.addHandler(handler)
        root.propagate = False
        _configured = True
    root.setLevel(_LEVELS.get(max(0, min(2, verbosity)), logging.INFO))
    return root


def get_logger(name: str = "") -> logging.Logger:
    """Child logger under the configured ``nds_tpu`` channel."""
    configure()
    return logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)
