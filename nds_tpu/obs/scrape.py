"""Live scrape endpoint: the first wire-visible operator surface.

A stdlib-``http.server`` daemon thread serving three read-only routes
against the process's observability registries and system tables
(``obs/system_tables.py``) — deliberately ahead of ROADMAP item 3's RPC
front door, because the operator surface has to exist before the data
plane goes cross-process:

- ``GET /metrics``  — Prometheus text exposition of the whole metrics
  registry (``METRICS.export_prometheus()``: counters as ``*_total``,
  histograms as cumulative ``_bucket``/``_sum``/``_count`` with labels);
- ``GET /healthz``  — liveness JSON: status, uptime, queries served,
  queue depth — the probe a load balancer or k8s liveness check hits;
- ``GET /query?sql=SELECT...`` — run one ``system.*`` statement through
  the host-only introspection path and return ``{columns, rows}`` JSON.
  ONLY system tables are queryable over the wire: the endpoint is an
  operator tool, not a data API, so a statement touching user tables is
  refused with 403 before any planning happens.

Start via ``ServiceConfig.metrics_port`` (the QueryService owns the
lifetime), ``scripts/metrics_server.py`` (standalone, can serve a saved
query-log JSONL), or directly::

    srv = MetricsServer(session, port=0).start()   # 0 = ephemeral
    ... http://127.0.0.1:{srv.port}/metrics ...
    srv.stop()

Requests never touch the device lane, the statement lock, or the
admission queue — scraping a saturated service perturbs nothing (the
guarantee ``Session.system_query`` provides; pinned by tests).
"""
from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .metrics import METRICS
from .log import get_logger

#: hard caps on the operator surface: longest accepted URL (the request
#: line IS the whole query payload on this GET-only endpoint) and the
#: largest Content-Length a request may declare — oversized requests are
#: refused with a typed JSON status, never buffered or half-parsed
MAX_URL_BYTES = 16 << 10
MAX_BODY_BYTES = 64 << 10
#: parse_qs field cap: bounds query-string parsing work per request
MAX_QUERY_FIELDS = 32


class _Handler(BaseHTTPRequestHandler):
    server_version = "nds-tpu-obs/1"

    # the owning MetricsServer installs itself on the server object
    @property
    def _owner(self) -> "MetricsServer":
        return self.server._owner          # type: ignore[attr-defined]

    def log_message(self, fmt, *args):       # quiet: route to the obs log
        get_logger().debug("scrape: " + fmt % args)

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, doc: dict) -> None:
        self._send(code, json.dumps(doc).encode(),
                   "application/json; charset=utf-8")

    def do_GET(self):                                      # noqa: N802
        try:
            if len(self.requestline) > MAX_URL_BYTES:
                self._send_json(414, {"error": "request line too long",
                                      "limit_bytes": MAX_URL_BYTES})
                return
            try:
                declared = int(self.headers.get("Content-Length") or 0)
            except (TypeError, ValueError):
                declared = -1
            if declared < 0 or declared > MAX_BODY_BYTES:
                self._send_json(413, {"error": "request body too large",
                                      "limit_bytes": MAX_BODY_BYTES})
                return
            parsed = urllib.parse.urlsplit(self.path)
            route = parsed.path.rstrip("/") or "/"
            if route == "/metrics":
                self._send(200, METRICS.export_prometheus().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif route == "/healthz":
                self._send_json(200, self._owner.health())
            elif route == "/query":
                self._do_query(parsed.query)
            else:
                self._send_json(404, {"error": f"no route {route!r}",
                                      "routes": ["/metrics", "/healthz",
                                                 "/query?sql=..."]})
        except BrokenPipeError:
            pass
        except Exception as e:       # one request must never kill the server
            try:
                self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    def _do_query(self, query_string: str) -> None:
        try:
            params = urllib.parse.parse_qs(
                query_string, max_num_fields=MAX_QUERY_FIELDS)
        except ValueError as e:
            # malformed or abusive query string is a 400 with a typed JSON
            # body — never a traceback, never a 500
            self._send_json(400, {"error": f"malformed query string: {e}"})
            return
        sql = (params.get("sql") or [""])[0].strip()
        if not sql:
            self._send_json(400, {"error": "missing ?sql= parameter"})
            return
        session = self._owner.session
        if session is None:
            self._send_json(503, {"error": "no session attached"})
            return
        try:
            table = session.system_query(sql, label="scrape")
        except ValueError as e:
            # non-system tables / parse-level refusals: the wire surface
            # serves INTROSPECTION only
            self._send_json(403, {"error": str(e)})
            return
        except Exception as e:
            self._send_json(400, {"error": f"{type(e).__name__}: {e}"})
            return
        from ..engine.arrow_bridge import to_arrow
        arrow = to_arrow(table)
        self._send_json(200, {
            "columns": arrow.column_names,
            "rows": [list(r.values()) for r in arrow.to_pylist()],
            "row_count": arrow.num_rows})


class MetricsServer:
    """Owns one ThreadingHTTPServer on a daemon thread.

    ``port=0`` binds an OS-assigned ephemeral port (tests); the bound
    port reads back from :attr:`port` after :meth:`start`."""

    def __init__(self, session=None, port: int = 0,
                 host: str = "127.0.0.1"):
        self.session = session
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.monotonic()

    def health(self) -> dict:
        snap = METRICS.snapshot()
        return {"status": "ok",
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "queries_run": snap.get("queries_run", 0),
                "system_queries": snap.get("system_queries", 0),
                "service_queue_depth": snap.get("service_queue_depth", 0),
                "query_failures": snap.get("query_failures", 0)}

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd._owner = self        # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._t0 = time.monotonic()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="obs-scrape")
        self._thread.start()
        get_logger().info(
            f"scrape endpoint: http://{self.host}:{self.port}/metrics")
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
