"""Typed per-query execution stats.

``Session.last_exec_stats`` used to be an untyped dict assembled in two
divergent code paths (the in-core executor path and the streaming morsel
path), and every PR grew new ad-hoc keys. ``ExecStats`` is the one typed
shape both paths construct; the session installs it through a single
method (``Session._finish_exec_stats``), keeping a dict view
(``to_dict``) for every existing consumer — bench/power JSON, tests, and
report summaries read the same keys as before.

Field groups:
- execution mode + device timing (every backend path);
- compile-segmentation counters (multi-unit plans);
- streaming/morsel counters (out-of-core queries);
- failure observability: host-fallback reasons and ALL prefetch errors
  (the old path kept only the first staging-thread failure).
Unknown executor-surfaced keys ride ``extra`` verbatim so a new stat in
the device layer never silently vanishes from reports.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


#: executor last_stats keys with first-class fields (everything else
#: passes through ``extra``)
_EXECUTOR_FIELDS = ("mode", "device_ms", "precompile_s", "nojit_reason",
                    "transient", "spec_mismatch", "segments", "segments_run",
                    "seg_device_ms")


@dataclass
class ExecStats:
    """One query execution's observability record."""
    # -- mode + device timing ------------------------------------------------
    mode: str = ""           # record|compile+run|compiled|eager|adopted|
    #                          streaming (the session's out-of-core path)
    device_ms: Optional[float] = None
    precompile_s: Optional[float] = None
    nojit_reason: Optional[str] = None
    transient: Optional[str] = None
    spec_mismatch: Optional[str] = None
    # -- compile segmentation ------------------------------------------------
    segments: Optional[int] = None
    segments_run: Optional[int] = None
    seg_device_ms: Optional[float] = None
    # -- streaming -----------------------------------------------------------
    jobs: Optional[int] = None
    morsels: Optional[int] = None
    morsel_rows: Optional[int] = None
    re_records: Optional[int] = None
    shared_scan: Optional[bool] = None
    scan_passes: Optional[int] = None
    tables_streamed: Optional[int] = None
    branches_served: Optional[int] = None
    fused_groups: Optional[int] = None
    bytes_uploaded: Optional[int] = None
    morsels_per_table: Optional[dict] = None
    narrow_lanes: Optional[bool] = None
    lane_spec: Optional[dict] = None
    # -- encoded execution (EngineConfig.encoded_exec) -----------------------
    #: whether dictionary/RLE wire encodings were eligible for this run
    encoded_exec: Optional[bool] = None
    #: per-table per-column chosen encoding tags ("plain"/"dict[k]"/"rle[r]")
    enc_spec: Optional[dict] = None
    #: upload bytes the encodings removed vs the plain narrow-lane layout
    enc_bytes_saved: Optional[int] = None
    #: decode_col sites that materialized values during this run's traces
    decode_sites: Optional[int] = None
    #: column slots those decodes materialized (rows x sites) — group keys
    #: that stay on codes keep this far below morsels x capacity
    decode_rows: Optional[int] = None
    #: per-table host-side Arrow->engine morsel decode wall (ms) — the
    #: staging-thread bottleneck, finally measurable
    host_decode_ms: Optional[dict] = None
    # -- sharded morsel execution (EngineConfig.mesh_shards) -----------------
    #: data-parallel replica count the streamed groups ran on (None = off)
    mesh_shards: Optional[int] = None
    #: scan groups whose morsels actually dispatched over the mesh
    sharded_groups: Optional[int] = None
    #: per-device ingress of the per-morsel partial all_gathers (ring model)
    collective_bytes: Optional[int] = None
    #: measured wall of the partial-gather dispatches
    collective_ms: Optional[float] = None
    # -- pallas kernels (EngineConfig.pallas_ops) ----------------------------
    #: the validated op subset active for this execution (None = flag off)
    pallas_ops: Optional[list] = None
    #: why the XLA lowering served despite the flag (platform/import/mesh)
    pallas_fallback_reason: Optional[str] = None
    # -- query service (nds_tpu/service) -------------------------------------
    #: wall spent between service admission and execution start (ms) — the
    #: service-mode latency decomposition: latency = queue_wait + execute
    queue_wait_ms: Optional[float] = None
    #: co-served queries: how many OTHER admitted queries rode the same
    #: compiled dispatch (compatible-plan batching); None = not batched
    batched_with: Optional[int] = None
    #: the query's ``service/ticket`` root span id — joins this stats
    #: record to its span subtree in a Chrome-trace/JSONL export (None
    #: outside the service, 0 when tracing was disabled at submit)
    trace_id: Optional[int] = None
    # -- per-plan-node actuals (obs/profile.py) ------------------------------
    #: {TypeName#k label: actual row count} — the row counts the engine
    #: ALREADY computes riding out for free: schedule-check values on the
    #: compiled path (group counts, join build/probe sizes), morsel/partial/
    #: final counts on the streamed path, exact per-node counts under
    #: profiled (EXPLAIN ANALYZE) execution. Labels match verify.py
    #: findings and PlanProfile nodes (same TypeName#k minting).
    node_stats: Optional[dict] = None
    # -- device-memory watermarks (obs/profile.DEVICE_MEM) -------------------
    #: high-water mark of tracked device bytes during THIS statement
    mem_peak_bytes: Optional[int] = None
    #: tracked device bytes live when the statement finished
    mem_live_bytes: Optional[int] = None
    #: scan-budget headroom above the statement's peak (budget - peak;
    #: None when the budget is unbounded)
    mem_headroom_bytes: Optional[int] = None
    # -- failure observability -----------------------------------------------
    fallback_reasons: list = field(default_factory=list)
    #: EVERY staging-thread failure of the run ("Type: message"), not just
    #: the first — repeated prefetch degradation is a pattern, not an event
    prefetch_error_details: list = field(default_factory=list)
    #: forward-compat passthrough for executor keys without a field
    extra: dict = field(default_factory=dict)

    # -- constructors (the ONE place each path builds stats) -----------------
    @classmethod
    def from_executor(cls, last_stats: dict,
                      fallbacks: Optional[list] = None) -> "ExecStats":
        """Typed view of ``JaxExecutor.last_stats`` (in-core path)."""
        known = {k: last_stats[k] for k in _EXECUTOR_FIELDS
                 if k in last_stats}
        extra = {k: v for k, v in last_stats.items()
                 if k not in _EXECUTOR_FIELDS}
        # per-node actuals the executor attributed from its capacity-
        # schedule checks ride the first-class field, not the passthrough
        node_stats = extra.pop("node_rows", None)
        return cls(fallback_reasons=list(fallbacks or ()),
                   node_stats=node_stats, extra=extra, **known)

    @classmethod
    def streaming(cls, *, jobs: int, morsels: int, morsel_rows: int,
                  re_records: int, shared_scan: bool, scan_passes: int,
                  tables_streamed: int, branches_served: int,
                  fused_groups: int, bytes_uploaded: int,
                  morsels_per_table: dict, narrow_lanes: bool,
                  lane_spec: dict,
                  encoded_exec: Optional[bool] = None,
                  enc_spec: Optional[dict] = None,
                  enc_bytes_saved: Optional[int] = None,
                  decode_sites: Optional[int] = None,
                  decode_rows: Optional[int] = None,
                  host_decode_ms: Optional[dict] = None,
                  prefetch_error_details: Optional[list] = None,
                  fallbacks: Optional[list] = None,
                  mesh_shards: Optional[int] = None,
                  sharded_groups: Optional[int] = None,
                  collective_bytes: Optional[int] = None,
                  collective_ms: Optional[float] = None,
                  node_stats: Optional[dict] = None) -> "ExecStats":
        """Typed record of one out-of-core (morsel-streamed) execution."""
        return cls(mode="streaming", jobs=jobs, morsels=morsels,
                   morsel_rows=morsel_rows, re_records=re_records,
                   shared_scan=shared_scan, scan_passes=scan_passes,
                   tables_streamed=tables_streamed,
                   branches_served=branches_served,
                   fused_groups=fused_groups, bytes_uploaded=bytes_uploaded,
                   morsels_per_table=dict(morsels_per_table),
                   narrow_lanes=narrow_lanes, lane_spec=dict(lane_spec),
                   encoded_exec=encoded_exec,
                   enc_spec=dict(enc_spec) if enc_spec is not None else None,
                   enc_bytes_saved=enc_bytes_saved,
                   decode_sites=decode_sites, decode_rows=decode_rows,
                   host_decode_ms=host_decode_ms,
                   mesh_shards=mesh_shards, sharded_groups=sharded_groups,
                   collective_bytes=collective_bytes,
                   collective_ms=collective_ms,
                   node_stats=node_stats,
                   prefetch_error_details=list(prefetch_error_details or ()),
                   fallback_reasons=list(fallbacks or ()))

    # -- views ---------------------------------------------------------------
    def to_dict(self) -> dict:
        """Backward-compatible dict view: exactly the keys the untyped
        ``last_exec_stats`` carried (None fields dropped, legacy
        ``prefetch_errors``/``prefetch_error`` aliases preserved)."""
        out: dict = {}
        if self.mode:
            out["mode"] = self.mode
        for k in ("device_ms", "precompile_s", "nojit_reason", "transient",
                  "spec_mismatch", "segments", "segments_run",
                  "seg_device_ms", "jobs", "morsels", "morsel_rows",
                  "re_records", "shared_scan", "scan_passes",
                  "tables_streamed", "branches_served", "fused_groups",
                  "bytes_uploaded", "morsels_per_table", "narrow_lanes",
                  "lane_spec", "encoded_exec", "enc_spec",
                  "enc_bytes_saved", "decode_sites", "decode_rows",
                  "host_decode_ms", "mesh_shards", "sharded_groups",
                  "collective_bytes", "collective_ms",
                  "pallas_ops", "pallas_fallback_reason",
                  "queue_wait_ms", "batched_with", "trace_id",
                  "node_stats", "mem_peak_bytes", "mem_live_bytes",
                  "mem_headroom_bytes"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        out.update(self.extra)
        if self.fallback_reasons:
            out["fallback_reasons"] = list(self.fallback_reasons)
        if self.prefetch_error_details:
            out["prefetch_errors"] = len(self.prefetch_error_details)
            out["prefetch_error"] = self.prefetch_error_details[0]
            out["prefetch_error_details"] = list(self.prefetch_error_details)
        return out
