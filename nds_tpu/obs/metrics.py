"""Unified metrics registry: typed counters/gauges for the whole engine.

Before this module every layer grew its own ad-hoc numbers — bench JSON
keys per PR, ``last_exec_stats`` dict entries, stderr one-liners. One
registry gives every layer (session, device, executor, streaming,
resilience, throughput, runners) a single place to write and every report
a single place to read: ``METRICS.snapshot()`` lands verbatim in
``bench.py`` / ``power.py`` JSON and ``scripts/trace_report.py``.

Counters are monotonic per process; runners take a snapshot before a unit
of work and report the ``delta`` so per-query/per-phase numbers come out
of process-lifetime totals. Everything is lock-protected — staging
threads, deadline workers, and compile pools all write concurrently —
and every metric a registry creates shares that REGISTRY's value lock,
so ``snapshot()`` is one consistent cut across all metrics (no torn
multi-metric deltas in power/bench summaries).

Three metric types:

- :class:`Counter` — monotonic; per-unit views come from ``delta``.
- :class:`Gauge` — last-written value (queue depths, in-flight counts).
- :class:`Histogram` — a latency/size distribution over fixed log-spaced
  buckets with exact count/sum/min/max, a ``quantile(p)`` whose error is
  bounded by the bucket spacing (documented on the class), mergeable/
  diffable snapshots, and optional label sets (tenant, template) so
  per-tenant p50/p95/p99 are readable live from the registry.
"""
from __future__ import annotations

import bisect
import math
import threading
from typing import Optional, Union

Number = Union[int, float]


class Counter:
    """Monotonic counter. ``inc`` only; never reset outside tests."""
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = "",
                 lock: Optional[threading.RLock] = None):
        self.name = name
        self.help = help
        self._value: Number = 0
        self._lock = lock if lock is not None else threading.RLock()

    def inc(self, n: Number = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> Number:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-written value (queue depths, in-flight counts)."""
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = "",
                 lock: Optional[threading.RLock] = None):
        self.name = name
        self.help = help
        self._value: Number = 0
        self._lock = lock if lock is not None else threading.RLock()

    def set(self, v: Number) -> None:
        with self._lock:
            self._value = v

    def add(self, n: Number = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> Number:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


# -- histograms ---------------------------------------------------------------

#: log-spaced bucket upper bounds (milliseconds): ratio 2^(1/3) per bucket
#: from 0.01 ms to ~21 million ms (~6 h) — 94 buckets plus an implicit
#: +Inf overflow. One fixed global ladder means every snapshot merges with
#: every other snapshot bucket-for-bucket (multi-process rollups, window
#: diffs) without negotiation.
BUCKET_RATIO = 2.0 ** (1.0 / 3.0)
BUCKET_BOUNDS = tuple(0.01 * 2.0 ** (i / 3.0) for i in range(94))


class Histogram:
    """A distribution over the fixed log-spaced bucket ladder.

    Exact ``count``/``sum``/``min``/``max`` ride beside the bucket counts,
    so means and extremes are precise; only interior quantiles pay the
    bucketing error.

    **Quantile error bound (documented contract):** ``quantile(p)``
    returns the geometric midpoint of the bucket containing the
    nearest-rank p-th sample (the same rank convention as
    ``exact_quantile``), clamped to the exact observed [min, max]. The
    true sample at that rank lies in the same bucket, so the returned
    value is within a factor of sqrt(BUCKET_RATIO) ≈ 1.123 of it — a
    relative error of at most ~12.3% in either direction (exactly 0 at
    the extremes p=0/p=1 and whenever the distribution collapses to one
    sample, thanks to the min/max clamp and exact extreme tracking).
    ``quantile_from_snapshot`` applies the same rule to exported
    snapshots.
    """
    __slots__ = ("name", "help", "labels", "_counts", "_overflow", "_count",
                 "_sum", "_min", "_max", "_lock")

    def __init__(self, name: str, help: str = "",
                 labels: Optional[dict] = None,
                 lock: Optional[threading.RLock] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._counts = [0] * len(BUCKET_BOUNDS)
        self._overflow = 0
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._lock = lock if lock is not None else threading.RLock()

    def observe(self, v: Number) -> None:
        v = float(v)
        i = bisect.bisect_left(BUCKET_BOUNDS, v)
        with self._lock:
            if i < len(self._counts):
                self._counts[i] += 1
            else:
                self._overflow += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, p: float) -> Optional[float]:
        """p in [0, 1]; None on an empty histogram. Error bound: see the
        class docstring (within a factor sqrt(BUCKET_RATIO) of exact)."""
        with self._lock:
            return quantile_from_snapshot(self._snapshot_locked(), p)

    def snapshot(self) -> dict:
        """Mergeable/diffable export: exact count/sum/min/max plus the
        SPARSE nonzero buckets as [le_ms, count] pairs (le=None is the
        +Inf overflow). Merging two snapshots (``merge_snapshots``) gives
        exactly the histogram of the union of their samples."""
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        out = {"count": self._count, "sum": round(self._sum, 6),
               "min": self._min, "max": self._max,
               "buckets": [[BUCKET_BOUNDS[i], n]
                           for i, n in enumerate(self._counts) if n]}
        if self._overflow:
            out["buckets"].append([None, self._overflow])
        return out

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(BUCKET_BOUNDS)
            self._overflow = 0
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None


def quantile_from_snapshot(snap: dict, p: float) -> Optional[float]:
    """The histogram quantile rule applied to an exported snapshot (same
    error bound as ``Histogram.quantile``): geometric bucket midpoint,
    clamped to the snapshot's exact [min, max]."""
    count = snap.get("count", 0)
    if not count:
        return None
    p = min(1.0, max(0.0, p))
    if p <= 0.0 and snap.get("min") is not None:
        return snap["min"]      # the extremes are tracked exactly
    if p >= 1.0 and snap.get("max") is not None:
        return snap["max"]
    # nearest-rank, the SAME convention as exact_quantile: the bucket
    # bound only holds when both sides talk about the same sample (at a
    # bimodal cliff, adjacent ranks can sit in different modes)
    rank = min(count, max(1, int(round(p * (count - 1))) + 1))
    seen = 0
    le = None
    for bound, n in snap.get("buckets", ()):
        seen += n
        if seen >= rank:
            le = bound
            break
    lo, hi = snap.get("min"), snap.get("max")
    if le is None:          # overflow bucket (or malformed): exact max
        return hi
    mid = le / (BUCKET_RATIO ** 0.5)    # geometric midpoint of (le/r, le]
    if lo is not None:
        mid = max(mid, lo)
    if hi is not None:
        mid = min(mid, hi)
    return mid


def merge_snapshots(a: dict, b: dict) -> dict:
    """Combine two histogram snapshots into the snapshot of the union of
    their samples. Associative and commutative (bucket counts add; exact
    count/sum add; min/max combine), so shard-level snapshots roll up in
    any order."""
    buckets: dict = {}
    for snap in (a, b):
        for le, n in snap.get("buckets", ()):
            buckets[le] = buckets.get(le, 0) + n
    mins = [s["min"] for s in (a, b) if s.get("min") is not None]
    maxs = [s["max"] for s in (a, b) if s.get("max") is not None]
    finite = sorted((le, n) for le, n in buckets.items() if le is not None)
    if None in buckets:
        finite.append((None, buckets[None]))
    return {"count": a.get("count", 0) + b.get("count", 0),
            "sum": round(a.get("sum", 0.0) + b.get("sum", 0.0), 6),
            "min": min(mins) if mins else None,
            "max": max(maxs) if maxs else None,
            "buckets": [[le, n] for le, n in finite]}


def diff_snapshot(now: dict, before: dict) -> dict:
    """Per-window view: ``now`` minus an earlier ``before`` of the same
    histogram (bucket counts are monotonic, so the difference is exactly
    the histogram of the samples observed in between). min/max cannot be
    un-merged, so the window inherits now's — quantiles stay inside the
    window's buckets regardless; only the clamp loosens."""
    buckets: dict = {le: n for le, n in now.get("buckets", ())}
    for le, n in before.get("buckets", ()):
        buckets[le] = buckets.get(le, 0) - n
    finite = sorted((le, n) for le, n in buckets.items()
                    if le is not None and n > 0)
    if buckets.get(None, 0) > 0:
        finite.append((None, buckets[None]))
    return {"count": now.get("count", 0) - before.get("count", 0),
            "sum": round(now.get("sum", 0.0) - before.get("sum", 0.0), 6),
            "min": now.get("min"), "max": now.get("max"),
            "buckets": [[le, n] for le, n in finite]}


def exact_quantile(sorted_vals: list, p: float) -> float:
    """Nearest-rank quantile over an already-sorted sample list — the
    exact reference the histogram quantile is checked against (and the
    helper service_bench/PERF cross-checks use instead of each script
    growing a private percentile())."""
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1, int(round(p * (len(sorted_vals) - 1))))
    return sorted_vals[k]


#: labeled histogram series per family before new label sets collapse
#: into the base (unlabeled) series — an abusive tenant/template explosion
#: degrades per-label resolution instead of growing memory unboundedly
HISTOGRAM_MAX_SERIES = 4096

#: the cardinality-cap fold has been logged already (once per process;
#: the ``histogram_series_overflow`` counter keeps the full count)
_OVERFLOW_LOGGED = False


_LABEL_BAD = str.maketrans({c: "_" for c in '{}",=\\\n\r\t'})


def _clean_labels(labels: dict) -> dict:
    """Label values are caller-provided (tenant names come off the wire):
    normalize the characters that would make series names ambiguous or
    break the Prometheus text exposition (quotes, separators, newlines,
    control chars) to underscores, once, at ingestion."""
    return {k: str(v).translate(_LABEL_BAD) for k, v in labels.items()}


def _series_name(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    """Named metric store; get-or-create semantics so layers never race
    over registration order.

    Every metric this registry creates shares ONE registry-level value
    lock, so :meth:`snapshot` reads all of them as a single atomic cut:
    a delta computed from two snapshots can never show metric A's update
    from a unit of work without metric B's (the torn-read class power/
    bench summaries used to be exposed to). Multi-metric updates that
    must land atomically against snapshots run under :meth:`locked`.
    Histograms live in their own namespace (a distribution named like an
    existing counter is fine — e.g. the ``service_queue_wait_ms`` total
    counter and the distribution of the same name coexist)."""

    def __init__(self) -> None:
        # registration lock (the dicts); reentrant: the labeled-series
        # overflow path re-enters histogram() for the base series
        self._lock = threading.RLock()
        self._values = threading.RLock()       # every metric's value lock
        self._metrics: dict[str, Union[Counter, Gauge]] = {}
        self._hists: dict[str, Histogram] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help, lock=self._values)
                self._metrics[name] = m
            elif not isinstance(m, Counter):
                raise TypeError(f"metric {name!r} is a {type(m).__name__}")
            return m

    def gauge(self, name: str, help: str = "") -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Gauge(name, help, lock=self._values)
                self._metrics[name] = m
            elif not isinstance(m, Gauge):
                raise TypeError(f"metric {name!r} is a {type(m).__name__}")
            return m

    def histogram(self, name: str, help: str = "", **labels) -> Histogram:
        """Get-or-create one histogram series: the base series (no
        labels) or a labeled child (``histogram("service_latency_ms",
        tenant="dash", template="a1b2")``). Children inherit the family
        help; past HISTOGRAM_MAX_SERIES labeled series the base series
        absorbs new label sets (resolution degrades, memory does not) —
        the fold is counted in ``histogram_series_overflow`` and logged
        ONCE per process, so a tenant/template cardinality explosion is
        visible instead of silently flattening the per-label views.
        Label values are sanitized (quotes/separators/newlines ->
        underscore): tenant names are caller-provided."""
        labels = _clean_labels(labels) if labels else labels
        key = _series_name(name, labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                if labels and len(self._hists) >= HISTOGRAM_MAX_SERIES:
                    self._note_series_overflow(key)
                    return self.histogram(name, help)
                if not help:
                    base = self._hists.get(name)
                    help = base.help if base is not None else ""
                h = Histogram(name, help, labels, lock=self._values)
                self._hists[key] = h
            elif help and not h.help:
                h.help = help
            return h

    def _note_series_overflow(self, key: str) -> None:
        """A labeled series fell into the base series at the cardinality
        cap: count every fold (``histogram_series_overflow``) and log the
        first one — called under the registration lock, so the inc rides
        the reentrant path (the counter shares this registry's locks)."""
        global _OVERFLOW_LOGGED
        c = self._metrics.get("histogram_series_overflow")
        if isinstance(c, Counter):
            c.inc()
        if not _OVERFLOW_LOGGED:
            _OVERFLOW_LOGGED = True
            from .log import get_logger
            get_logger().warning(
                "histogram label cardinality cap reached "
                f"({HISTOGRAM_MAX_SERIES} series): new labeled series "
                f"(first: {key!r}) fold into their base series — "
                "per-label resolution degrades, memory does not")

    def locked(self):
        """The shared value lock, for callers that update several metrics
        as one logical event: ``with METRICS.locked(): a.inc(); b.inc()``
        guarantees no snapshot observes a without b."""
        return self._values

    def snapshot(self) -> dict[str, Number]:
        """{name: value} for every counter/gauge — the uniform block
        runners embed in their JSON output. One atomic cut: taken under
        the shared value lock, so concurrent updates are either fully in
        or fully out (histograms export via :meth:`histograms`)."""
        with self._lock:
            items = sorted(self._metrics.items())
        with self._values:
            return {name: m._value for name, m in items}

    def histograms(self) -> dict[str, dict]:
        """{series: snapshot} for every histogram series (base + labeled),
        one atomic cut like :meth:`snapshot`. Series names render labels
        Prometheus-style: ``service_latency_ms{tenant=dash,template=x}``;
        each snapshot carries its ``labels`` dict for structured
        consumers (obs_report, service_bench)."""
        with self._lock:
            items = sorted(self._hists.items())
        out = {}
        with self._values:
            for key, h in items:
                snap = h._snapshot_locked()
                if not snap["count"]:
                    continue
                snap["name"] = h.name
                if h.labels:
                    snap["labels"] = dict(h.labels)
                out[key] = snap
        return out

    def percentiles(self, name: str, ps: tuple = (0.5, 0.95, 0.99),
                    ) -> list[dict]:
        """Live SLO view of one histogram family: one row per series —
        the base (all-traffic) series first, then every label set sorted
        by the highest requested quantile so the slowest tenants/
        templates lead. Each row carries count/mean/min/max and the
        requested quantiles (``p50`` etc.)."""
        rows = []
        for key, snap in self.histograms().items():
            if snap["name"] != name:
                continue
            row = {"series": key, "labels": snap.get("labels", {}),
                   "count": snap["count"],
                   "mean": round(snap["sum"] / snap["count"], 3),
                   "min": snap["min"], "max": snap["max"]}
            for p in ps:
                q = quantile_from_snapshot(snap, p)
                row[f"p{int(p * 100)}"] = round(q, 3) if q is not None \
                    else None
            rows.append(row)
        top = f"p{int(max(ps) * 100)}"
        rows.sort(key=lambda r: (bool(r["labels"]), -(r[top] or 0)))
        return rows

    def rows(self) -> list[tuple]:
        """(name, kind, value, help) for every counter/gauge, one atomic
        cut under the shared value lock — the system.metrics snapshot
        source (typed kind beside the value, unlike :meth:`snapshot`)."""
        with self._lock:
            items = sorted(self._metrics.items())
        with self._values:
            return [(name,
                     "counter" if isinstance(m, Counter) else "gauge",
                     m._value, m.help) for name, m in items]

    def delta(self, before: dict[str, Number]) -> dict[str, Number]:
        """Per-unit-of-work view: current snapshot minus ``before``,
        dropping zero rows (counters are process-lifetime totals)."""
        now = self.snapshot()
        out = {}
        for name, v in now.items():
            d = v - before.get(name, 0)
            if d:
                out[name] = round(d, 3) if isinstance(d, float) else d
        return out

    def describe(self) -> dict[str, str]:
        """{name: help} metrics glossary (README / trace_report) —
        counters, gauges, and histogram FAMILIES (one row per family,
        not per labeled series)."""
        with self._lock:
            out = {name: m.help for name, m in self._metrics.items()}
            for h in self._hists.values():
                if not h.labels and h.name not in out:
                    out[h.name] = h.help
        return dict(sorted(out.items()))

    def export_prometheus(self) -> str:
        """Prometheus text exposition of the whole registry. Counters
        export as ``<name>_total``, gauges verbatim, histograms as the
        standard ``_bucket{le=...}/_sum/_count`` triplet (cumulative
        buckets over the fixed ladder, labels preserved) — so the name
        collision between a ``*_ms`` total counter and the distribution
        of the same name stays legal after suffixing."""
        with self._lock:
            scalars = sorted(self._metrics.items())
            hists = sorted(self._hists.items())
        lines: list[str] = []
        with self._values:
            for name, m in scalars:
                kind = "counter" if isinstance(m, Counter) else "gauge"
                out_name = f"{name}_total" if kind == "counter" else name
                if m.help:
                    lines.append(f"# HELP {out_name} {m.help}")
                lines.append(f"# TYPE {out_name} {kind}")
                lines.append(f"{out_name} {m._value}")
            seen_family = set()
            for _key, h in hists:
                if h.name not in seen_family:
                    seen_family.add(h.name)
                    if h.help:
                        lines.append(f"# HELP {h.name} {h.help}")
                    lines.append(f"# TYPE {h.name} histogram")
                base = ",".join(f'{k}="{h.labels[k]}"'
                                for k in sorted(h.labels))
                cum = 0
                for i, n in enumerate(h._counts):
                    cum += n
                    if n:
                        le = f"{BUCKET_BOUNDS[i]:.6g}"
                        sep = "," if base else ""
                        lines.append(f'{h.name}_bucket{{{base}{sep}le='
                                     f'"{le}"}} {cum}')
                sep = "," if base else ""
                lines.append(f'{h.name}_bucket{{{base}{sep}le="+Inf"}} '
                             f"{cum + h._overflow}")
                lab = f"{{{base}}}" if base else ""
                lines.append(f"{h.name}_sum{lab} {round(h._sum, 6)}")
                lines.append(f"{h.name}_count{lab} {h._count}")
        return "\n".join(lines) + "\n"

    def export_json(self) -> dict:
        """One structured export of everything: the scalar snapshot, the
        histogram snapshots, and the glossary — the artifact obs_report
        and the metrics gate read."""
        return {"metrics": self.snapshot(), "histograms": self.histograms(),
                "describe": self.describe()}

    def reset(self) -> None:
        """Zero every metric (tests only; counters are monotonic in
        production). Labeled histogram series unregister entirely —
        tests must not see a previous test's tenants."""
        with self._lock:
            metrics = list(self._metrics.values())
            hists = list(self._hists.values())
            self._hists = {k: h for k, h in self._hists.items()
                           if not h.labels}
        for m in metrics:
            m._reset()
        for h in hists:
            h._reset()


#: the process-global registry; every engine layer writes through it.
METRICS = MetricsRegistry()

# Pre-registered engine metrics: importing a layer must not be required
# before its counters appear in snapshots, and attribute-style access
# (``from ..obs.metrics import QUERIES_RUN``) is typo-safe at import time.
QUERIES_RUN = METRICS.counter(
    "queries_run", "sql() calls executed by any Session")
QUERY_FAILURES = METRICS.counter(
    "query_failures", "timed query runs that raised (power runner)")
RETRIES = METRICS.counter(
    "retries", "retry attempts consumed by any RetryPolicy/BenchReport")
FAULT_FIRINGS = METRICS.counter(
    "fault_point_firings", "armed fault specs triggered (FaultRegistry)")
PROGRAM_CACHE_HITS = METRICS.counter(
    "program_cache_hits", "compiled/recorded plan entries served from cache")
PROGRAM_CACHE_MISSES = METRICS.counter(
    "program_cache_misses", "plan entries recorded fresh (first sighting)")
PROGRAMS_ADOPTED = METRICS.counter(
    "programs_adopted", "cross-stream shared-program adoptions")
COMPILES = METRICS.counter(
    "compiles", "whole-plan XLA compilations (jit first-run + precompile)")
SCAN_PASSES = METRICS.counter(
    "scan_passes", "streamed morsel loops over a big table")
MORSELS = METRICS.counter(
    "morsels", "morsels executed across all streamed queries")
BYTES_UPLOADED = METRICS.counter(
    "bytes_uploaded", "host->device bytes staged for streamed morsels")
HOST_FALLBACKS = METRICS.counter(
    "host_fallbacks", "plan nodes served by the host oracle backend")
PREFETCH_ERRORS = METRICS.counter(
    "prefetch_errors", "staging-thread failures (morsel restaged sync)")
STREAM_RESTARTS = METRICS.counter(
    "stream_restarts", "throughput stream attempts beyond the first")
REPLAY_MISMATCHES = METRICS.counter(
    "replay_mismatches", "compiled schedules invalidated by capacity drift")
# Pallas kernel dispatches (pallas_kernels): counted at build time — once
# per kernel instantiation under a jit trace, once per call in eager record
PALLAS_SORT_CALLS = METRICS.counter(
    "pallas_sort_calls", "tiled bitonic sort_pairs dispatches (pallas)")
PALLAS_GROUPBY_CALLS = METRICS.counter(
    "pallas_groupby_calls", "fused seg_reduce partial-agg dispatches (pallas)")
PALLAS_GATHER_CALLS = METRICS.counter(
    "pallas_gather_calls", "VMEM-staged take_many dispatches (pallas)")
# Encoded execution (device.plan_encodings): dictionary/RLE wire encodings
DICT_UPLOADS_SAVED = METRICS.counter(
    "dict_uploads_saved", "device codebook uploads served from the "
    "per-group cache instead of re-uploading")
DECODE_SITES = METRICS.counter(
    "decode_sites", "encoded columns materialized to values (decode_col: "
    "arithmetic/aggregate/output sites)")
HOST_DECODE_MS = METRICS.counter(
    "host_decode_ms", "host-side Arrow->engine morsel decode wall (ms) "
    "summed over streamed tables — the staging-thread bottleneck "
    "ROADMAP item 2 (device-side page decode) exists to remove")
# Concurrent query service (nds_tpu/service): admission, queueing, batching
SERVICE_ADMITTED = METRICS.counter(
    "service_admitted", "queries accepted into the service queue")
SERVICE_REJECTED = METRICS.counter(
    "service_rejected", "queries refused at admission (queue full / "
    "service closed) — typed AdmissionRejected, never a pile-up")
SERVICE_DEADLINE_EXPIRED = METRICS.counter(
    "service_deadline_expired", "admitted queries whose per-tenant "
    "deadline expired before execution started (typed DeadlineExceeded)")
SERVICE_BATCHES = METRICS.counter(
    "service_batches", "batched dispatches: one compiled program served "
    "a stacked parameter matrix for several compatible queries")
SERVICE_BATCHED_QUERIES = METRICS.counter(
    "service_batched_queries", "queries served through a batched dispatch "
    "(including parameter-identical duplicates deduplicated in-batch)")
SERVICE_QUEUE_WAIT_MS = METRICS.counter(
    "service_queue_wait_ms", "total wall (ms) admitted queries spent "
    "waiting between admission and execution start")
SERVICE_QUEUE_DEPTH = METRICS.gauge(
    "service_queue_depth", "queries currently admitted but not finished "
    "(the admission-control pressure signal)")
# Self-healing service mechanisms (chaos-hardened serving): the breaker,
# retry budget, and program quarantine the chaos campaigns exercise —
# all exactly zero on a healthy run (the metrics gate pins the first
# two strict-zero on its clean workload)
CIRCUIT_TRIPS = METRICS.counter(
    "circuit_trips", "per-error-class circuit-breaker trips (incl. "
    "half-open probe failures re-opening): admission then refuses work "
    "with typed CircuitOpen until a probe succeeds")
RETRY_BUDGET_SPENT = METRICS.counter(
    "retry_budget_spent", "transient ticket failures re-dispatched off "
    "the device lane by the service's bounded retry budget")
QUARANTINED_PROGRAMS = METRICS.counter(
    "quarantined_programs", "shared compiled-program cache entries "
    "evicted after repeated faults/ReplayMismatches (re-recorded fresh "
    "on next use instead of poisoning every adopter)")
LIFECYCLE_PHASE_RETRIES = METRICS.counter(
    "lifecycle_phase_retries", "scored-lifecycle phases re-run after a "
    "failure (lifecycle.LifecycleRunner phase_attempts)")
# Semantic result cache (engine/result_cache.py): cross-client result
# reuse keyed by parameterized-plan fingerprint + parameter vector, with
# subsumption proofs and incremental view maintenance from LF_*/DF_*
# deltas — all opt-in, all exactly zero when the cache is disabled (the
# metrics gate pins result_cache_hits strict-zero on its clean workload)
RESULT_CACHE_HITS = METRICS.counter(
    "result_cache_hits", "queries answered from the semantic result "
    "cache's exact tier (no planning, no device dispatch)")
RESULT_CACHE_MISSES = METRICS.counter(
    "result_cache_misses", "result-cache lookups that fell through to "
    "normal execution (cold text, stale generation, expired TTL, or no "
    "provable subsumption)")
RESULT_CACHE_SUBSUMPTION_HITS = METRICS.counter(
    "result_cache_subsumption_hits", "queries answered by re-filtering a "
    "cached coarser aggregate after a containment proof (provably-"
    "narrower filter over the same group keys — no scan, no upload)")
RESULT_CACHE_IVM_UPDATES = METRICS.counter(
    "result_cache_ivm_updates", "cached aggregate entries updated in "
    "place from a maintenance insert/delete delta (mergeable partial "
    "state merged/recomputed instead of invalidated)")
RESULT_CACHE_INVALIDATIONS = METRICS.counter(
    "result_cache_invalidations", "result-cache entries dropped for "
    "staleness (table generation moved, TTL expired, or a delta the "
    "entry could not absorb)")
# EXPLAIN ANALYZE / per-plan-node runtime profiles (obs/profile.py): all
# exactly zero when profiling is off (the metrics gate pins both
# strict-zero on its clean, profiling-off workload)
PROFILED_QUERIES = METRICS.counter(
    "profiled_queries", "queries executed in profiled (EXPLAIN ANALYZE) "
    "mode: eager node-by-node walk with per-node wall/rows/bytes, "
    "bit-identical results (Session.explain_analyze / "
    "EngineConfig.profile_plans)")
CARDINALITY_MISESTIMATES = METRICS.counter(
    "cardinality_misestimates", "estimate-vs-actual cardinality audit "
    "findings above the misestimate ratio threshold (profiled runs only: "
    "planner static size assumption vs exact per-node row count)")
HISTOGRAM_SERIES_OVERFLOW = METRICS.counter(
    "histogram_series_overflow", "labeled histogram series folded into "
    "their base series at the HISTOGRAM_MAX_SERIES cardinality cap "
    "(per-label resolution degraded; logged once per process)")
# Device-memory watermark accounting (obs/profile.DEVICE_MEM): the live
# set of tracked device allocations (to_device/pack_table/stage_sharded
# uploads + the codebook cache) and its process-lifetime peak — compiled-
# program intermediates are NOT tracked (see DeviceMemTracker)
DEVICE_LIVE_BYTES = METRICS.gauge(
    "device_live_bytes", "tracked device-resident bytes currently live "
    "(uploads + codebook cache; freed buffers subtract)")
DEVICE_PEAK_BYTES = METRICS.gauge(
    "device_peak_bytes", "process-lifetime peak of device_live_bytes — "
    "the high-water mark headroom checks compare to the HBM budget")
# System tables + durable query log (obs/system_tables.py, obs/
# query_log.py): all exactly zero when the log is disabled and no
# system.* statement runs (the metrics gate pins all three strict-zero
# on its clean workload — the zero-cost contract for the disabled path)
SYSTEM_QUERIES = METRICS.counter(
    "system_queries", "system.* statements served through the host-only "
    "introspection path (Session.system_query / the service's admission "
    "bypass / the /query scrape endpoint) — never a device dispatch")
QUERY_LOG_ROWS = METRICS.counter(
    "query_log_rows", "statement rows appended to the durable query log "
    "(in-memory ring + optional JSONL sink; obs/query_log.py)")
QUERY_LOG_ROTATIONS = METRICS.counter(
    "query_log_rotations", "query-log JSONL files rolled by the "
    "size-capped rotation (oldest rotated file deleted past max_files)")
# Transactional warehouse (warehouse.py _snapshots log): atomic multi-
# table commits, aborts, and crash recovery — all exactly zero on a
# query-only workload (the metrics gate pins all three strict-zero on
# its clean, maintenance-free workload) and zero whenever
# EngineConfig.warehouse_transactions is off
TXN_COMMITS = METRICS.counter(
    "txn_commits", "warehouse transactions published atomically (one "
    "version record + CURRENT swing naming every table's manifest "
    "version — the cross-table commit point)")
TXN_ROLLBACKS = METRICS.counter(
    "txn_rollbacks", "warehouse transactions aborted (per-table "
    "manifests truncated back to the transaction's base versions) plus "
    "explicit rollback_to_version restores")
TXN_RECOVERIES = METRICS.counter(
    "txn_recoveries", "orphaned in-progress transactions discarded at "
    "warehouse open (crash recovery: each table back to max(base, "
    "published) — never a blend of pre- and post-commit state)")
# Adaptive execution (engine/feedback.py): the feedback stats store
# closing the loop from observed actuals to the next sighting's plans —
# all exactly zero when EngineConfig.adaptive_plans is off (no store is
# constructed; the metrics gate pins all three strict-zero on its clean,
# adaptation-off workload)
FEEDBACK_HITS = METRICS.counter(
    "feedback_hits", "streamed scan groups whose capacity schedule was "
    "right-sized from the feedback store's observed per-decision maxima "
    "instead of morsel-bound inflation (a ceiling hint: an "
    "under-observed actual re-records, never mis-answers)")
FEEDBACK_REFRESHES = METRICS.counter(
    "feedback_refreshes", "drift-sentinel refreshes: a template's "
    "observed profile diverged from its own history past the drift "
    "ratio, so the stale history was replaced and the generation bumped "
    "(the next sighting re-records instead of replaying stale caps)")
ADAPTIVE_REPLANS = METRICS.counter(
    "adaptive_replans", "streamed re-records driven by feedback: a "
    "cached schedule invalidated by a moved profile generation, or an "
    "adapted (right-sized) schedule overflowed by an under-observed "
    "actual (ReplayMismatch fallback — correctness preserved)")
# Distributed serving (service/frontdoor.py + fair scheduling in
# service/service.py): all exactly zero when the front door is not
# started and fair_queue/preemption/inflight_dedup are off (the
# defaults) — the metrics gate pins all six strict-zero on its clean
# in-process workload (the everything-opt-in contract)
FRONTDOOR_REQUESTS = METRICS.counter(
    "frontdoor_requests", "requests served by the Arrow-IPC front door "
    "(query/ping/cache_snapshot/cache_validate frames across all client "
    "connections; service/frontdoor.py)")
FRONTDOOR_ERRORS = METRICS.counter(
    "frontdoor_errors", "front-door requests answered with a typed error "
    "frame (the resilience class + fields reconstructed client-side) or "
    "dropped by an injected connection fault")
SERVICE_PREEMPTIONS = METRICS.counter(
    "service_preemptions", "interactive tickets served at a streamed "
    "query's morsel-boundary yield point (the batch scan paused between "
    "scan groups, the device lane ran the short query, the stream "
    "resumed its cached state — bit-identity preserved)")
SERVICE_INFLIGHT_DEDUP = METRICS.counter(
    "service_inflight_dedup", "admitted tickets that parked on an "
    "already-in-flight ticket with the same (fingerprint, params, "
    "snapshot) key instead of re-entering the planner queue — followers "
    "attach to the leader's shared result cell")
RESULT_CACHE_SNAPSHOTS = METRICS.counter(
    "result_cache_snapshots", "exact-tier result-cache exports served "
    "over the front door (Arrow-IPC snapshot frames warming a client "
    "process's local cache)")
FRONTDOOR_CLIENT_CACHE_HITS = METRICS.counter(
    "frontdoor_client_cache_hits", "client-side cache hits served from a "
    "snapshot-warmed local result set after the per-lookup validation "
    "handshake confirmed the entry's generations are still current")

# Service latency distributions (histogram families): the base series
# aggregates every query; the service also records per-(tenant, template)
# children, so per-tenant p50/p95/p99 and the top-K slow templates are
# readable LIVE from the registry (METRICS.percentiles) instead of being
# recomputed by each bench script. queue_wait + plan + exec + materialize
# decompose service_latency_ms end-to-end (materialize lands on the
# client thread AFTER completion, so it rides beside, not inside).
SERVICE_LATENCY_HIST = METRICS.histogram(
    "service_latency_ms", "per-query service latency distribution, "
    "admission -> completion (labeled by tenant + template fingerprint)")
SERVICE_QUEUE_WAIT_HIST = METRICS.histogram(
    "service_queue_wait_ms", "distribution of the wall between admission "
    "and execution start (the counter of the same name keeps the total)")
SERVICE_PLAN_HIST = METRICS.histogram(
    "service_plan_ms", "planner-stage wall distribution "
    "(parse/plan/parameterize on the planner worker threads)")
SERVICE_EXEC_HIST = METRICS.histogram(
    "service_exec_ms", "device-lane execution wall distribution "
    "(batched dispatch or serial session run)")
SERVICE_MATERIALIZE_HIST = METRICS.histogram(
    "service_materialize_ms", "deferred result-materialization wall "
    "distribution (client-thread Table conversion in Ticket.result)")
QUERY_LATENCY_HIST = METRICS.histogram(
    "query_latency_ms", "timed single-caller query latency distribution "
    "(bench timed runs / power stream, labeled by template)")
