"""Unified metrics registry: typed counters/gauges for the whole engine.

Before this module every layer grew its own ad-hoc numbers — bench JSON
keys per PR, ``last_exec_stats`` dict entries, stderr one-liners. One
registry gives every layer (session, device, executor, streaming,
resilience, throughput, runners) a single place to write and every report
a single place to read: ``METRICS.snapshot()`` lands verbatim in
``bench.py`` / ``power.py`` JSON and ``scripts/trace_report.py``.

Counters are monotonic per process; runners take a snapshot before a unit
of work and report the ``delta`` so per-query/per-phase numbers come out
of process-lifetime totals. Everything is lock-protected — staging
threads, deadline workers, and compile pools all write concurrently.
"""
from __future__ import annotations

import threading
from typing import Union

Number = Union[int, float]


class Counter:
    """Monotonic counter. ``inc`` only; never reset outside tests."""
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value: Number = 0
        self._lock = threading.Lock()

    def inc(self, n: Number = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> Number:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-written value (queue depths, in-flight counts)."""
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value: Number = 0
        self._lock = threading.Lock()

    def set(self, v: Number) -> None:
        with self._lock:
            self._value = v

    def add(self, n: Number = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> Number:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


class MetricsRegistry:
    """Named metric store; get-or-create semantics so layers never race
    over registration order."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Union[Counter, Gauge]] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help)
                self._metrics[name] = m
            elif not isinstance(m, Counter):
                raise TypeError(f"metric {name!r} is a {type(m).__name__}")
            return m

    def gauge(self, name: str, help: str = "") -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Gauge(name, help)
                self._metrics[name] = m
            elif not isinstance(m, Gauge):
                raise TypeError(f"metric {name!r} is a {type(m).__name__}")
            return m

    def snapshot(self) -> dict[str, Number]:
        """{name: value} for every registered metric — the uniform block
        runners embed in their JSON output."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.value for name, m in sorted(items)}

    def delta(self, before: dict[str, Number]) -> dict[str, Number]:
        """Per-unit-of-work view: current snapshot minus ``before``,
        dropping zero rows (counters are process-lifetime totals)."""
        now = self.snapshot()
        out = {}
        for name, v in now.items():
            d = v - before.get(name, 0)
            if d:
                out[name] = round(d, 3) if isinstance(d, float) else d
        return out

    def describe(self) -> dict[str, str]:
        """{name: help} metrics glossary (README / trace_report)."""
        with self._lock:
            return {name: m.help for name, m in sorted(self._metrics.items())}

    def reset(self) -> None:
        """Zero every metric (tests only; counters are monotonic in
        production)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._reset()


#: the process-global registry; every engine layer writes through it.
METRICS = MetricsRegistry()

# Pre-registered engine metrics: importing a layer must not be required
# before its counters appear in snapshots, and attribute-style access
# (``from ..obs.metrics import QUERIES_RUN``) is typo-safe at import time.
QUERIES_RUN = METRICS.counter(
    "queries_run", "sql() calls executed by any Session")
QUERY_FAILURES = METRICS.counter(
    "query_failures", "timed query runs that raised (power runner)")
RETRIES = METRICS.counter(
    "retries", "retry attempts consumed by any RetryPolicy/BenchReport")
FAULT_FIRINGS = METRICS.counter(
    "fault_point_firings", "armed fault specs triggered (FaultRegistry)")
PROGRAM_CACHE_HITS = METRICS.counter(
    "program_cache_hits", "compiled/recorded plan entries served from cache")
PROGRAM_CACHE_MISSES = METRICS.counter(
    "program_cache_misses", "plan entries recorded fresh (first sighting)")
PROGRAMS_ADOPTED = METRICS.counter(
    "programs_adopted", "cross-stream shared-program adoptions")
COMPILES = METRICS.counter(
    "compiles", "whole-plan XLA compilations (jit first-run + precompile)")
SCAN_PASSES = METRICS.counter(
    "scan_passes", "streamed morsel loops over a big table")
MORSELS = METRICS.counter(
    "morsels", "morsels executed across all streamed queries")
BYTES_UPLOADED = METRICS.counter(
    "bytes_uploaded", "host->device bytes staged for streamed morsels")
HOST_FALLBACKS = METRICS.counter(
    "host_fallbacks", "plan nodes served by the host oracle backend")
PREFETCH_ERRORS = METRICS.counter(
    "prefetch_errors", "staging-thread failures (morsel restaged sync)")
STREAM_RESTARTS = METRICS.counter(
    "stream_restarts", "throughput stream attempts beyond the first")
REPLAY_MISMATCHES = METRICS.counter(
    "replay_mismatches", "compiled schedules invalidated by capacity drift")
# Pallas kernel dispatches (pallas_kernels): counted at build time — once
# per kernel instantiation under a jit trace, once per call in eager record
PALLAS_SORT_CALLS = METRICS.counter(
    "pallas_sort_calls", "tiled bitonic sort_pairs dispatches (pallas)")
PALLAS_GROUPBY_CALLS = METRICS.counter(
    "pallas_groupby_calls", "fused seg_reduce partial-agg dispatches (pallas)")
PALLAS_GATHER_CALLS = METRICS.counter(
    "pallas_gather_calls", "VMEM-staged take_many dispatches (pallas)")
# Encoded execution (device.plan_encodings): dictionary/RLE wire encodings
DICT_UPLOADS_SAVED = METRICS.counter(
    "dict_uploads_saved", "device codebook uploads served from the "
    "per-group cache instead of re-uploading")
DECODE_SITES = METRICS.counter(
    "decode_sites", "encoded columns materialized to values (decode_col: "
    "arithmetic/aggregate/output sites)")
# Concurrent query service (nds_tpu/service): admission, queueing, batching
SERVICE_ADMITTED = METRICS.counter(
    "service_admitted", "queries accepted into the service queue")
SERVICE_REJECTED = METRICS.counter(
    "service_rejected", "queries refused at admission (queue full / "
    "service closed) — typed AdmissionRejected, never a pile-up")
SERVICE_DEADLINE_EXPIRED = METRICS.counter(
    "service_deadline_expired", "admitted queries whose per-tenant "
    "deadline expired before execution started (typed DeadlineExceeded)")
SERVICE_BATCHES = METRICS.counter(
    "service_batches", "batched dispatches: one compiled program served "
    "a stacked parameter matrix for several compatible queries")
SERVICE_BATCHED_QUERIES = METRICS.counter(
    "service_batched_queries", "queries served through a batched dispatch "
    "(including parameter-identical duplicates deduplicated in-batch)")
SERVICE_QUEUE_WAIT_MS = METRICS.counter(
    "service_queue_wait_ms", "total wall (ms) admitted queries spent "
    "waiting between admission and execution start")
SERVICE_QUEUE_DEPTH = METRICS.gauge(
    "service_queue_depth", "queries currently admitted but not finished "
    "(the admission-control pressure signal)")
