"""Durable query log: one flat row per completed statement.

Every ExecStats the engine produces is rich but ephemeral — it describes
the LAST statement, lives in Python, and dies with the process. The query
log is the durable, queryable complement: at ``Session._finish_exec_stats``
time (and at every service ticket's completion) the typed stats flatten
into ONE flat dict — O(row) work, no plan walk — appended to

- a bounded in-memory ring (``system.query_log`` serves SQL over it live:
  ``SELECT tenant, wall_ms FROM system.query_log`` works mid-overload), and
- an opt-in buffered JSONL file with size-capped rotation, so every
  scored run leaves a self-describing artifact ``scripts/slo_report.py``
  can compute per-tenant SLO attainment and burn rates from offline.

Disabled (the default) a record is ONE attribute read — the engine adds
zero counters and zero allocation per statement. Enable with
``EngineConfig.query_log`` / ``--query_log`` on the run drivers /
``NDS_TPU_QUERY_LOG=1`` (or ``=<path>`` for the JSONL sink).

The row schema is FROZEN (``COLUMNS``): tests pin the column names and
dtypes, ``system.query_log`` materializes exactly these columns, and the
JSONL rows are the ring rows verbatim (ring<->file equivalence is a
tested property). Unknown fields are dropped at record time rather than
growing the schema silently.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

#: the frozen row schema: (column, engine dtype). Dtypes are the engine's
#: logical dtypes ("int" = int64, "float" = f64, "str") — the same names
#: system_tables pins into the system.query_log catalog schema. Nullable
#: everywhere; absent fields land as None/null.
COLUMNS = (
    ("ts", "float"),            # unix seconds at completion
    ("seq", "int"),             # per-process total order
    ("source", "str"),          # session | service
    ("label", "str"),           # query label (runners pass "query9" etc.)
    ("tenant", "str"),          # service tenant ("" outside the service)
    ("template", "str"),        # parameterized-plan fingerprint prefix
    ("trace_id", "int"),        # joins the row to its span subtree
    ("status", "str"),          # "ok" | error class name
    ("error", "str"),           # error message ("" when ok)
    ("wall_ms", "float"),       # statement wall (service: admission->done)
    ("queue_ms", "float"),      # admission -> execution start
    ("plan_ms", "float"),       # planner-stage wall (service path)
    ("exec_ms", "float"),       # device-lane/dispatch wall (service path)
    ("materialize_ms", "float"),  # deferred client-side conversion, when
    #                               it happened before the row was cut
    ("rows", "int"),            # result rows (None when not materialized)
    ("bytes_uploaded", "int"),  # host->device bytes staged (streamed)
    ("mode", "str"),            # exec mode (compiled/adopted/streaming/...)
    ("cache_mode", "str"),      # "" | exact | subsumed (result cache)
    ("mesh_shards", "int"),     # data-parallel replicas (streamed shards)
    ("morsels", "int"),         # morsels executed (streamed)
    ("mem_peak_bytes", "int"),  # device-memory high-water mark
    ("node_stats", "str"),      # {TypeName#k: actual rows} as JSON —
    #                             offline tooling (slo_report,
    #                             explain_report --audit, the feedback
    #                             store's replay_log) reconstructs
    #                             per-node actuals without explain folders
    ("preempted", "int"),       # interactive tickets served at this
    #                             streamed query's morsel-boundary yield
    #                             points (0 outside the fair scheduler)
)

COLUMN_NAMES = tuple(c for c, _ in COLUMNS)

#: ring rows kept in memory (system.query_log's window) by default
DEFAULT_CAPACITY = 4096
#: JSONL rows buffered before a write syscall (flushed on rotation/close)
FLUSH_EVERY = 64
#: rotation default: the active file rolls past this size
DEFAULT_MAX_BYTES = 64 << 20
#: rotated files kept (oldest deleted first); the active file rides beside
DEFAULT_MAX_FILES = 4


def _cache_mode(mode: str) -> str:
    if mode == "cached":
        return "exact"
    if mode == "cached_subsumed":
        return "subsumed"
    return ""


def flatten_stats(stats, **ctx) -> dict:
    """One ExecStats -> one flat row dict (O(fields), no plan walk).

    ``ctx`` carries what the stats record does not know (source, label,
    tenant, wall_ms, error, ...); unknown keys are dropped so the frozen
    schema cannot grow by accident."""
    row = dict.fromkeys(COLUMN_NAMES)
    if stats is not None:
        row["mode"] = stats.mode or None
        row["cache_mode"] = _cache_mode(stats.mode) or None
        row["trace_id"] = stats.trace_id
        row["queue_ms"] = stats.queue_wait_ms
        row["bytes_uploaded"] = stats.bytes_uploaded
        row["mesh_shards"] = stats.mesh_shards
        row["morsels"] = stats.morsels
        row["mem_peak_bytes"] = stats.mem_peak_bytes
        if stats.node_stats:
            row["node_stats"] = json.dumps(stats.node_stats,
                                           sort_keys=True)
    for k, v in ctx.items():
        if k in row and v is not None:
            row[k] = v
    if row["status"] is None:
        row["status"] = type(row["error"]).__name__ \
            if isinstance(row["error"], BaseException) else \
            ("error" if row["error"] else "ok")
    if isinstance(row["error"], BaseException):
        row["error"] = str(row["error"])
    return row


class QueryLog:
    """Process-wide statement log (one instance: ``QUERY_LOG``).

    The ring append and the JSONL buffer share one lock; rotation renames
    the active file to ``<path>.<k>`` with a MONOTONIC k (1, 2, ...) so
    lexicographic sort of a rotation set is chronological, and deletes
    the oldest rotated file beyond ``max_files``."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=DEFAULT_CAPACITY)
        self._seq = 0
        self.path: Optional[str] = None
        self.max_bytes = DEFAULT_MAX_BYTES
        self.max_files = DEFAULT_MAX_FILES
        self.flush_every = FLUSH_EVERY
        self._buf: list[str] = []
        self._file_bytes = 0
        self._rot_seq = 0

    # -- control -------------------------------------------------------------
    def configure(self, enabled: bool = True,
                  capacity: Optional[int] = None,
                  path: Optional[str] = None,
                  max_bytes: Optional[int] = None,
                  max_files: Optional[int] = None,
                  flush_every: Optional[int] = None,
                  clear: bool = True) -> "QueryLog":
        with self._lock:
            if capacity is not None:
                self._ring = deque(self._ring, maxlen=capacity)
            if path is not None:
                self.path = path or None
                self._file_bytes = (os.path.getsize(path)
                                    if path and os.path.exists(path) else 0)
            if max_bytes is not None:
                self.max_bytes = max_bytes
            if max_files is not None:
                self.max_files = max_files
            if flush_every is not None:
                self.flush_every = max(1, flush_every)
            if clear:
                self._ring.clear()
                self._buf = []
                self._seq = 0
                self._rot_seq = 0
            self.enabled = enabled
        return self

    def close(self) -> None:
        """Flush the JSONL buffer and disable."""
        self.flush()
        with self._lock:
            self.enabled = False

    # -- recording -----------------------------------------------------------
    def record(self, stats=None, **ctx) -> Optional[dict]:
        """Append one statement row (no-op while disabled). ``stats`` is
        the ExecStats to flatten; ``ctx`` the out-of-band fields (source,
        label, tenant, wall_ms, error, rows, ...)."""
        if not self.enabled:
            return None
        row = flatten_stats(stats, **ctx)
        row["ts"] = round(time.time(), 3)
        flush_now = None
        with self._lock:
            self._seq += 1
            row["seq"] = self._seq
            self._ring.append(row)
            if self.path:
                self._buf.append(json.dumps(row))
                if len(self._buf) >= self.flush_every:
                    flush_now = self._drain_locked()
        from .metrics import QUERY_LOG_ROWS
        QUERY_LOG_ROWS.inc()
        if flush_now:
            self._write(flush_now)
        return row

    # -- JSONL sink ----------------------------------------------------------
    def _drain_locked(self) -> list[str]:
        out, self._buf = self._buf, []
        return out

    def flush(self) -> None:
        with self._lock:
            pending = self._drain_locked() if self.path else []
        if pending:
            self._write(pending)

    def _write(self, lines: list[str]) -> None:
        """Append buffered rows; rotate first when the active file would
        cross max_bytes (checked against the TRACKED size, one stat-free
        comparison per flush)."""
        payload = "\n".join(lines) + "\n"
        with self._lock:
            path = self.path
            if path is None:
                return
            if self._file_bytes and \
                    self._file_bytes + len(payload) > self.max_bytes:
                self._rotate_locked()
            self._file_bytes += len(payload)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a") as f:
            f.write(payload)

    def _rotate_locked(self) -> None:
        """Roll the active file to ``<path>.<k>`` (monotonic k) and drop
        the oldest rotated file past max_files. Called under the lock."""
        self._rot_seq += 1
        try:
            os.replace(self.path, f"{self.path}.{self._rot_seq}")
        except OSError:
            pass          # active file vanished: nothing to roll
        drop = self._rot_seq - self.max_files
        if drop >= 1:
            try:
                os.remove(f"{self.path}.{drop}")
            except OSError:
                pass
        self._file_bytes = 0
        from .metrics import QUERY_LOG_ROTATIONS
        QUERY_LOG_ROTATIONS.inc()

    # -- inspection ----------------------------------------------------------
    def rows(self) -> list[dict]:
        """The ring, oldest first (the system.query_log snapshot source)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def load_rows(self, rows) -> int:
        """Replay saved rows (a JSONL artifact) into the ring so
        ``system.query_log`` SQL works over an OFFLINE log — the
        scripts/slo_report.py dogfooding path. Returns rows loaded."""
        n = 0
        with self._lock:
            for r in rows:
                clean = {k: r.get(k) for k in COLUMN_NAMES}
                self._ring.append(clean)
                n += 1
            self._seq = max(self._seq,
                            max((r.get("seq") or 0 for r in self._ring),
                                default=0))
        return n


def read_jsonl(path: str) -> list[dict]:
    """Rows of one query-log JSONL file (rotated sets: pass each file;
    lexicographic filename order is chronological by construction)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


#: the process-global query log every statement completion reports into.
QUERY_LOG = QueryLog()

_env = os.environ.get("NDS_TPU_QUERY_LOG", "")
if _env and _env.lower() not in ("0", "false", "no", "off"):
    QUERY_LOG.configure(
        enabled=True,
        path=None if _env.lower() in ("1", "true", "yes", "on") else _env)
