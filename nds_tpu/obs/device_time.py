"""Per-program device-time attribution.

The engine's compiled-program boundary (``CompiledQuery.run``) is where
instrumentation must live (the Flare lesson, PAPERS.md): each dispatch is
one XLA program — a whole query, a CTE/rollup segment, or a fused morsel
group. Every run reports its measured wall time here under the program's
label, and the first compile contributes the program's static
``cost_analysis()`` FLOPs/bytes, so the registry can rank programs by
device time and compute a PER-PROGRAM roofline fraction — replacing the
single global ``roofline_frac`` with a sorted "top programs by device
time" table that names the kernel-work targets directly (ROADMAP item 1).

``device_ms`` includes the D2H result transfer (run() measures around one
``device_get``); on tunneled platforms that RTT is part of what the
program costs the stream, so it belongs in the attribution.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ProgramStat:
    """Accumulated execution record of one compiled program."""
    label: str
    runs: int = 0
    device_ms: float = 0.0          # summed measured dispatch+fetch wall
    max_ms: float = 0.0
    #: the program's first (compile+run) dispatch, kept separate so
    #: steady-state means — and the rooflines derived from them — are not
    #: diluted by one-time compile cost
    first_ms: Optional[float] = None
    flops: Optional[float] = None           # per-execution, cost_analysis
    bytes_accessed: Optional[float] = None  # per-execution, cost_analysis
    extra: dict = field(default_factory=dict)

    def steady_mean_ms(self) -> float:
        """Mean over steady-state (post-first) runs; falls back to the
        overall mean when only the first run exists."""
        if self.first_ms is not None and self.runs > 1:
            return (self.device_ms - self.first_ms) / (self.runs - 1)
        return self.device_ms / self.runs if self.runs else 0.0


class ProgramRegistry:
    """Thread-safe label -> ProgramStat accumulator (compile pools and
    concurrent streams report simultaneously)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._programs: dict[str, ProgramStat] = {}

    def record_run(self, label: str, device_ms: float,
                   first: bool = False) -> None:
        with self._lock:
            st = self._programs.get(label)
            if st is None:
                st = ProgramStat(label)
                self._programs[label] = st
            st.runs += 1
            st.device_ms += device_ms
            st.max_ms = max(st.max_ms, device_ms)
            if first and st.first_ms is None:
                st.first_ms = device_ms

    def record_cost(self, label: str, cost) -> None:
        """Attach a jax ``compiled.cost_analysis()`` result (dict, or the
        older list-of-dicts shape). Unknown shapes are ignored — cost data
        enriches the table, it never fails a run."""
        entry = None
        if isinstance(cost, dict):
            entry = cost
        elif isinstance(cost, (list, tuple)) and cost and \
                isinstance(cost[0], dict):
            entry = cost[0]
        if entry is None:
            return
        flops = entry.get("flops")
        bytes_accessed = entry.get("bytes accessed")
        with self._lock:
            st = self._programs.get(label)
            if st is None:
                st = ProgramStat(label)
                self._programs[label] = st
            if flops is not None:
                st.flops = float(flops)
            if bytes_accessed is not None:
                st.bytes_accessed = float(bytes_accessed)

    def total_ms(self) -> float:
        with self._lock:
            return sum(s.device_ms for s in self._programs.values())

    def table(self, bw_gbps: float = 100.0, top: Optional[int] = None
              ) -> list[dict]:
        """Sorted (desc by total device time) per-program rows.

        ``roofline_frac`` is per program: the fraction of the wire/HBM
        bandwidth `bw_gbps` the program's cost-analysis bytes would
        saturate over its mean measured run — the program-local version of
        the bench's global number, so the slowest-and-least-bound programs
        (the Pallas-kernel targets) sort to the top with their own
        utilization attached."""
        with self._lock:
            stats = sorted(self._programs.values(),
                           key=lambda s: s.device_ms, reverse=True)
        rows = []
        for s in stats[:top] if top else stats:
            mean_ms = s.steady_mean_ms()
            row = {
                "program": s.label,
                "runs": s.runs,
                "device_ms": round(s.device_ms, 3),
                "mean_ms": round(mean_ms, 3),
                "max_ms": round(s.max_ms, 3),
            }
            if s.first_ms is not None:
                row["first_ms"] = round(s.first_ms, 3)
            if s.flops is not None:
                row["flops"] = s.flops
            if s.bytes_accessed is not None:
                row["bytes_accessed"] = s.bytes_accessed
                if mean_ms > 0:
                    ideal_s = s.bytes_accessed / (bw_gbps * 1e9)
                    row["roofline_frac"] = round(
                        ideal_s / (mean_ms / 1e3), 5)
            rows.append(row)
        return rows

    def snapshot(self) -> dict[str, ProgramStat]:
        with self._lock:
            return dict(self._programs)

    def reset(self) -> None:
        with self._lock:
            self._programs = {}


#: process-global registry; CompiledQuery.run reports into it.
PROGRAMS = ProgramRegistry()


# ---------------------------------------------------------------------------
# fetch-based standalone timing (PERF.md measurement caveat, fixed at the
# source): on this tunneled platform ``block_until_ready`` returns when the
# dispatch is ACKNOWLEDGED, not when the result exists, so bare
# block-until-ready timings of standalone kernels read ~0 ms. Timing around
# a result FETCH (``jax.device_get``) closes the gap — the D2H round trip
# is part of what a program costs the stream anyway (see module docstring).
# On the host CPU backend arrays are already local and block_until_ready is
# an honest completion barrier, so the platform check keeps the cheap path.
# ---------------------------------------------------------------------------

def fetch_timing_required() -> bool:
    """True on accelerator/tunneled platforms where only a result fetch
    proves the computation ran to completion."""
    import jax
    try:
        return jax.devices()[0].platform != "cpu"
    except Exception:          # pragma: no cover - no backend at all
        return False


def timed_call(fn, *args) -> tuple[float, object]:
    """One measured call of ``fn(*args)``: returns (wall ms, host result).

    The completion barrier is a ``device_get`` fetch when the platform
    requires it, else ``block_until_ready`` (+ the same host conversion so
    both paths return comparable objects)."""
    import time as _time

    import jax
    t0 = _time.perf_counter()
    out = fn(*args)
    if fetch_timing_required():
        host = jax.device_get(out)
    else:
        host = jax.device_get(jax.block_until_ready(out))
    return (_time.perf_counter() - t0) * 1000.0, host


def measure_ms(fn, *args, iters: int = 3, warmup: int = 1,
               label: Optional[str] = None) -> float:
    """Best-of-`iters` fetch-based wall ms of ``fn(*args)`` after `warmup`
    untimed calls (compile excluded). With `label`, every timed run also
    reports into ``PROGRAMS`` so kernel microbenches surface in the same
    per-program attribution table as the engine's compiled queries."""
    for _ in range(max(0, warmup)):
        timed_call(fn, *args)
    best = float("inf")
    for _ in range(max(1, iters)):
        ms, _ = timed_call(fn, *args)
        best = min(best, ms)
        if label is not None:
            PROGRAMS.record_run(label, ms)
    return best


def coverage(table_rows: list[dict], measured_wall_ms: float) -> float:
    """Fraction of a measured wall-clock interval the per-program device
    times account for (the >=90% attribution acceptance check)."""
    if measured_wall_ms <= 0:
        return 0.0
    return sum(r["device_ms"] for r in table_rows) / measured_wall_ms


def format_table(rows: list[dict]) -> str:
    """Fixed-width text rendering of ``ProgramRegistry.table`` rows for
    stderr diagnostics / trace_report."""
    if not rows:
        return "(no programs recorded)"
    head = (f"{'program':<40} {'runs':>5} {'total_ms':>10} {'mean_ms':>9} "
            f"{'roofline':>9}")
    lines = [head, "-" * len(head)]
    for r in rows:
        rf = r.get("roofline_frac")
        lines.append(
            f"{r['program'][:40]:<40} {r['runs']:>5} {r['device_ms']:>10.1f} "
            f"{r['mean_ms']:>9.2f} "
            f"{(f'{rf:.4f}' if rf is not None else '-'):>9}")
    return "\n".join(lines)
