"""Observability layer: span tracing, metrics, device-time attribution.

Built BEFORE the kernel/sharding work (ROADMAP items 1-2) because the
engine could not say which operator in which query burns the chip's time
— this package is the instrument those PRs are measured with.

- :mod:`.trace`   — lifecycle span tracer (parse -> plan passes ->
  compile -> upload -> per-morsel exec -> finalize) with Chrome-trace /
  JSONL / aggregate exporters; near-zero cost disabled.
- :mod:`.metrics` — process-wide typed counter/gauge/histogram registry
  every layer writes through (one shared value lock per registry: every
  snapshot is an atomic cut); histograms carry {tenant, template} labels
  so per-tenant p50/p95/p99 read live; Prometheus/JSON exporters.
- :mod:`.flight`  — bounded ring of query-lifecycle events, JSONL-dumped
  on demand, on rejection storms, or when a fault point fires (the
  post-mortem artifact chaos runs assert against).
- :mod:`.device_time` — per-compiled-program measured device time +
  cost_analysis FLOPs/bytes, ranked with per-program roofline fractions.
- :mod:`.stats`   — the typed ``ExecStats`` replacing the untyped
  ``last_exec_stats`` dict (dict view preserved).
- :mod:`.profile` — EXPLAIN ANALYZE: per-plan-node runtime profiles
  under the verifier's stable TypeName#k identities, the
  estimate-vs-actual cardinality audit, and the device-memory watermark
  accountant (``DEVICE_MEM``) the upload paths write through.
- :mod:`.query_log` — durable query log: one flat row per completed
  statement (bounded ring + opt-in rotating JSONL) — the
  ``system.query_log`` source.
- :mod:`.system_tables` — the ``system`` catalog: metrics, histograms,
  query log, programs, result cache, device memory, flight ring, and
  catalog generations as SQL-queryable tables on the host-only path.
- :mod:`.scrape`  — stdlib-http scrape endpoint (``/metrics``,
  ``/healthz``, ``/query?sql=...``): the first wire-visible operator
  surface.
- :mod:`.log`     — ``logging``-based diagnostics channel with one
  verbosity knob, replacing raw stderr writes.
"""
from .trace import TRACER, span                                  # noqa: F401
from .metrics import METRICS                                     # noqa: F401
from .flight import FLIGHT                                       # noqa: F401
from .query_log import QUERY_LOG                                 # noqa: F401
from .device_time import PROGRAMS                                # noqa: F401
from .stats import ExecStats                                     # noqa: F401
from .profile import DEVICE_MEM, PlanProfile                     # noqa: F401
from .log import get_logger                                      # noqa: F401
