"""Throughput test: run N query streams concurrently.

Capability parity with the reference throughput harness (reference
nds/nds-throughput: xargs -P fans one full Spark app per stream;
nds/nds_bench.py:138-157 computes elapsed = max(stream end) - min(stream
start) by scraping the per-stream time logs). Here each stream is a full
power run; ``process`` mode launches one OS process per stream (the
reference's N-concurrent-apps shape — separate interpreters so the
streams contend only for the device, not the GIL), ``thread`` mode
multiplexes in-process sessions onto one device (cheap for tests and for
sharing a single compiled-query cache).
"""
from __future__ import annotations

import argparse
import csv
import os
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor


def stream_log_path(time_log_dir: str, stream: int) -> str:
    return os.path.join(time_log_dir, f"throughput_{stream}.csv")


def _run_stream_thread(input_prefix: str, stream_file: str, time_log: str,
                       **kwargs) -> None:
    from .power import run_query_stream
    run_query_stream(input_prefix, stream_file, time_log, **kwargs)


def _stream_cmd(input_prefix: str, stream_file: str, time_log: str,
                input_format: str, output_prefix: str | None,
                json_summary_folder: str | None,
                sub_queries: list[str] | None,
                property_file: str | None, backend: str | None,
                warmup: int = 0, decimal: str | None = None) -> list[str]:
    cmd = [sys.executable, "-m", "nds_tpu.power", input_prefix, stream_file,
           time_log, "--input_format", input_format]
    if warmup:
        cmd += ["--warmup", str(warmup)]
    if decimal:
        cmd += ["--decimal", decimal]
    if output_prefix:
        cmd += ["--output_prefix", output_prefix]
    if json_summary_folder:
        cmd += ["--json_summary_folder", json_summary_folder]
    if sub_queries:
        cmd += ["--sub_queries", ",".join(sub_queries)]
    if property_file:
        cmd += ["--property_file", property_file]
    if backend:
        cmd += ["--backend", backend]
    return cmd


def run_throughput(input_prefix: str, stream_dir: str, streams: list[int],
                   time_log_dir: str,
                   input_format: str = "parquet",
                   output_prefix: str | None = None,
                   json_summary_folder: str | None = None,
                   sub_queries: list[str] | None = None,
                   property_file: str | None = None,
                   backend: str | None = None,
                   mode: str = "process",
                   warmup: int = 0, decimal: str | None = None) -> float:
    """Run the given streams concurrently; returns elapsed seconds.

    Elapsed is max(stream Power End) - min(stream Power Start) over the
    written time logs, the reference's definition (nds_bench.py:138-157).
    """
    os.makedirs(time_log_dir, exist_ok=True)
    jobs = []
    for s in streams:
        stream_file = os.path.join(stream_dir, f"query_{s}.sql")
        log = stream_log_path(time_log_dir, s)
        out = os.path.join(output_prefix, f"stream_{s}") \
            if output_prefix else None
        jobs.append((stream_file, log, out))

    if mode == "process":
        procs = [subprocess.Popen(
            _stream_cmd(input_prefix, sf, log, input_format, out,
                        json_summary_folder, sub_queries, property_file,
                        backend, warmup, decimal))
            for sf, log, out in jobs]
        failed = [p.args for p in procs if p.wait() != 0]
        if failed:
            raise RuntimeError(f"throughput streams failed: {failed}")
    else:
        with ThreadPoolExecutor(max_workers=len(jobs)) as pool:
            futures = [pool.submit(
                _run_stream_thread, input_prefix, sf, log,
                input_format=input_format, output_prefix=out,
                json_summary_folder=json_summary_folder,
                sub_queries=sub_queries, property_file=property_file,
                backend=backend, warmup=warmup, decimal=decimal)
                for sf, log, out in jobs]
            for f in futures:
                f.result()

    return throughput_elapsed([log for _, log, _ in jobs])


def scrape_log(time_log: str) -> tuple[int, int]:
    """Return (power start ms, power end ms) from a power-run time log."""
    start = end = None
    with open(time_log) as f:
        for row in csv.reader(f):
            if not row:
                continue
            if row[0] == "Power Start Time":
                start = int(row[1])
            elif row[0] == "Power End Time":
                end = int(row[1])
    if start is None or end is None:
        raise ValueError(f"no sentinel rows in {time_log}")
    return start, end


def throughput_elapsed(time_logs: list[str]) -> float:
    spans = [scrape_log(p) for p in time_logs]
    return (max(e for _, e in spans) - min(s for s, _ in spans)) / 1000.0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="nds_tpu.throughput")
    p.add_argument("input_prefix")
    p.add_argument("stream_dir")
    p.add_argument("streams", help="comma-separated stream ids, e.g. 1,2,3,4")
    p.add_argument("time_log_dir")
    p.add_argument("--input_format", default="parquet")
    p.add_argument("--output_prefix", default=None)
    p.add_argument("--json_summary_folder", default=None)
    p.add_argument("--sub_queries", default=None)
    p.add_argument("--property_file", default=None)
    p.add_argument("--backend", default=None, choices=["jax", "numpy"])
    p.add_argument("--mode", default="process",
                   choices=["process", "thread"])
    p.add_argument("--warmup", type=int, default=0,
                   help="untimed pre-runs per query in each stream")
    p.add_argument("--decimal", default=None, choices=["f64", "i64"])
    a = p.parse_args(argv)
    ids = [int(s) for s in a.streams.split(",")]
    sub = a.sub_queries.split(",") if a.sub_queries else None
    elapsed = run_throughput(a.input_prefix, a.stream_dir, ids,
                             a.time_log_dir, a.input_format, a.output_prefix,
                             a.json_summary_folder, sub, a.property_file,
                             a.backend, a.mode, a.warmup, a.decimal)
    print(f"Throughput Test Time: {elapsed:.3f} seconds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
