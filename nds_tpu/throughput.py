"""Throughput test: run N query streams concurrently, supervised.

Capability parity with the reference throughput harness (reference
nds/nds-throughput: xargs -P fans one full Spark app per stream;
nds/nds_bench.py:138-157 computes elapsed = max(stream end) - min(stream
start) by scraping the per-stream time logs). Here each stream is a full
power run; ``process`` mode launches one OS process per stream (the
reference's N-concurrent-apps shape — separate interpreters so the
streams contend only for the device, not the GIL), ``thread`` mode
multiplexes in-process sessions onto one device (cheap for tests and for
sharing a single compiled-query cache), and ``service`` mode submits
EVERY stream's queries through one shared admission-controlled
QueryService over a single Session (nds_tpu/service): one warehouse
registration, one cross-client program cache, compatible queries from
different streams coalescing into batched dispatches — the interactive
multi-tenant shape, measured with the same per-stream time logs.

On top of the reference's detect-and-abort posture sits a supervisor
(resilience layer): each stream gets a wall-clock budget and up to N spawn
attempts — a crashed or hung stream is killed and restarted with
deterministic backoff instead of aborting the round; per-stream outcomes
land in a status CSV, and a round with permanently failed streams reports
the partial elapsed over the completed ones instead of a bare
RuntimeError.
"""
from __future__ import annotations

import argparse
import csv
import os
import subprocess
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from .resilience import (DeadlineExceeded, FAULTS, RetryPolicy,
                         run_with_deadline)


def stream_log_path(time_log_dir: str, stream: int) -> str:
    return os.path.join(time_log_dir, f"throughput_{stream}.csv")


def status_csv_path(time_log_dir: str) -> str:
    return os.path.join(time_log_dir, "throughput_status.csv")


class IncompleteStreamLog(ValueError):
    """A stream time log is missing or lacks its sentinel rows (the stream
    was interrupted before completing)."""


class ThroughputError(RuntimeError):
    """Streams failed permanently. Carries the partial elapsed over the
    streams that DID complete plus the failed stream ids, so callers keep
    the round's measurements instead of losing everything."""

    def __init__(self, message: str, partial_elapsed: float | None = None,
                 failed: list[int] | None = None):
        super().__init__(message)
        self.partial_elapsed = partial_elapsed
        self.failed = failed or []


@dataclass
class StreamStatus:
    """One stream's supervised outcome (a row of the status CSV)."""
    stream: int
    attempts: int = 0
    status: str = "Pending"     # Pending|Running|Completed|Failed|TimedOut
    error: str = ""
    restart_at: float = field(default=0.0, repr=False)


def _run_stream_thread(input_prefix: str, stream_file: str, time_log: str,
                       **kwargs) -> None:
    from .power import run_query_stream
    run_query_stream(input_prefix, stream_file, time_log, **kwargs)


def _run_stream_service(service, stream_file: str, time_log: str,
                        sub_queries: list[str] | None = None,
                        warmup: int = 0,
                        backend: str | None = None,
                        tenant: str = "default") -> None:
    """One stream's queries through a shared QueryService: same time-log
    contract as a power run (per-query rows + Power Start/End sentinels),
    but execution interleaves with every other stream on one session —
    queries wait in the service queue instead of contending for the GIL
    at full-plan granularity, and compatible templates across streams
    batch into shared dispatches."""
    import re as _re
    import time as _time

    from .power import _write_time_log, gen_sql_from_stream

    with open(stream_file) as f:
        query_dict = gen_sql_from_stream(f.read())
    if sub_queries:
        query_dict = {
            k: v for k, v in query_dict.items()
            if k in sub_queries
            or _re.sub(r"_part[12]$", "", k) in sub_queries}
    rows: list[tuple[str, int, int, int]] = []
    power_start = int(_time.time() * 1000)
    for name, sql in query_dict.items():
        statements = [s for s in sql.split(";") if s.strip()]
        for _ in range(warmup):
            for stmt in statements:
                service.sql(stmt, label=name, backend=backend,
                            tenant=tenant)
        q_start = int(_time.time() * 1000)
        for stmt in statements:
            service.sql(stmt, label=name, backend=backend, tenant=tenant)
        q_end = int(_time.time() * 1000)
        rows.append((name, q_start, q_end, q_end - q_start))
        _write_time_log(time_log, power_start, rows, None)
    _write_time_log(time_log, power_start, rows, int(_time.time() * 1000))


def _stream_cmd(input_prefix: str, stream_file: str, time_log: str,
                input_format: str, output_prefix: str | None,
                json_summary_folder: str | None,
                sub_queries: list[str] | None,
                property_file: str | None, backend: str | None,
                warmup: int = 0, decimal: str | None = None) -> list[str]:
    cmd = [sys.executable, "-m", "nds_tpu.power", input_prefix, stream_file,
           time_log, "--input_format", input_format]
    if warmup:
        cmd += ["--warmup", str(warmup)]
    if decimal:
        cmd += ["--decimal", decimal]
    if output_prefix:
        cmd += ["--output_prefix", output_prefix]
    if json_summary_folder:
        cmd += ["--json_summary_folder", json_summary_folder]
    if sub_queries:
        cmd += ["--sub_queries", ",".join(sub_queries)]
    if property_file:
        cmd += ["--property_file", property_file]
    if backend:
        cmd += ["--backend", backend]
    return cmd


def write_status_csv(path: str, statuses: list[StreamStatus]) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["stream", "attempts", "status", "error"])
        for s in sorted(statuses, key=lambda s: s.stream):
            w.writerow([s.stream, s.attempts, s.status, s.error])
    os.replace(tmp, path)   # atomic, like the time logs


def supervise_processes(jobs: list[tuple[int, list[str]]],
                        max_attempts: int = 1,
                        stream_timeout: float | None = None,
                        backoff_s: float = 1.0,
                        poll_s: float = 0.1,
                        spawn=subprocess.Popen,
                        clock=time.monotonic) -> list[StreamStatus]:
    """Supervise one OS process per stream: spawn, watch, kill on budget
    overrun, restart crashed/killed streams up to ``max_attempts`` with
    exponential backoff. ``jobs`` is [(stream_id, argv)]. Always kills any
    surviving children on the way out — an abandoned round (exception,
    Ctrl-C) never leaks sibling processes.
    """
    policy = RetryPolicy(max_attempts=max_attempts, backoff_s=backoff_s)
    statuses = {sid: StreamStatus(sid) for sid, _ in jobs}
    cmds = dict(jobs)
    live: dict[int, tuple] = {}        # sid -> (proc, started_at)
    waiting: list[int] = [sid for sid, _ in jobs]   # ready/backing-off

    def _spawn(sid: int) -> None:
        st = statuses[sid]
        st.attempts += 1
        if st.attempts > 1:
            from .obs.metrics import STREAM_RESTARTS
            STREAM_RESTARTS.inc()
        FAULTS.fire("stream.spawn", str(sid))
        live[sid] = (spawn(cmds[sid]), clock())
        st.status = "Running"

    def _attempt_failed(sid: int, status: str, error: str) -> None:
        st = statuses[sid]
        st.error = error
        if st.attempts < max_attempts:
            st.status = "Pending"
            st.restart_at = clock() + policy.backoff(st.attempts)
            waiting.append(sid)
        else:
            st.status = status

    try:
        while waiting or live:
            for sid in [s for s in waiting
                        if clock() >= statuses[s].restart_at]:
                waiting.remove(sid)
                try:
                    _spawn(sid)
                except Exception as e:   # spawn itself failed (fault point)
                    _attempt_failed(sid, "Failed",
                                    f"spawn: {type(e).__name__}: {e}")
            for sid, (proc, started) in list(live.items()):
                rc = proc.poll()
                if rc is None:
                    if stream_timeout and clock() - started > stream_timeout:
                        proc.kill()
                        proc.wait()
                        del live[sid]
                        _attempt_failed(
                            sid, "TimedOut",
                            f"killed after {stream_timeout}s budget")
                    continue
                del live[sid]
                if rc == 0:
                    statuses[sid].status = "Completed"
                    statuses[sid].error = ""
                else:
                    _attempt_failed(sid, "Failed", f"exit code {rc}")
            if waiting or live:
                time.sleep(poll_s)
    finally:
        # abandoned round (exception/interrupt): never leak children
        for proc, _ in live.values():
            proc.kill()
        for proc, _ in live.values():
            proc.wait()
    return list(statuses.values())


def _supervised_thread_stream(sid: int, run, max_attempts: int,
                              stream_timeout: float | None,
                              backoff_s: float) -> StreamStatus:
    """Thread-mode supervision for one stream: retry crashed attempts with
    backoff; a budget overrun ABANDONS the worker (threads cannot be
    killed) and is terminal — a restart would race the zombie attempt on
    the same time log."""
    policy = RetryPolicy(max_attempts=max_attempts, backoff_s=backoff_s)
    st = StreamStatus(sid)
    while st.attempts < max_attempts:
        st.attempts += 1
        if st.attempts > 1:
            from .obs.metrics import STREAM_RESTARTS
            STREAM_RESTARTS.inc()
        try:
            FAULTS.fire("stream.spawn", str(sid))
            if stream_timeout:
                run_with_deadline(run, stream_timeout,
                                  label=f"stream {sid}")
            else:
                run()
            st.status, st.error = "Completed", ""
            return st
        except DeadlineExceeded as e:
            st.status, st.error = "TimedOut", str(e)
            return st
        except Exception as e:
            st.status = "Failed"
            st.error = f"{type(e).__name__}: {e}"
            if st.attempts < max_attempts:
                time.sleep(policy.backoff(st.attempts))
    return st


def _write_service_obs(time_log_dir: str) -> None:
    """Service-mode observability artifacts beside the time logs: the
    per-tenant/per-stream SLO view (service_slo.json — counts, p50/p95/
    p99 per series, straight from the registry histograms) and, when the
    flight recorder is on (NDS_TPU_FLIGHT=1), the round's lifecycle ring
    as flight.jsonl — the post-mortem record a chaos round asserts on."""
    import json

    from .obs.flight import FLIGHT
    from .obs.metrics import METRICS

    rows = METRICS.percentiles("service_latency_ms")
    if rows:
        path = os.path.join(time_log_dir, "service_slo.json")
        with open(path, "w") as f:
            json.dump({"service_latency_ms": rows,
                       "histograms": {
                           k: v for k, v in METRICS.histograms().items()
                           if v["name"].startswith("service_")}}, f,
                      indent=2)
    if FLIGHT.enabled and FLIGHT.events():
        FLIGHT.dump_jsonl(os.path.join(time_log_dir, "flight.jsonl"))


def run_throughput(input_prefix: str, stream_dir: str, streams: list[int],
                   time_log_dir: str,
                   input_format: str = "parquet",
                   output_prefix: str | None = None,
                   json_summary_folder: str | None = None,
                   sub_queries: list[str] | None = None,
                   property_file: str | None = None,
                   backend: str | None = None,
                   mode: str = "process",
                   warmup: int = 0, decimal: str | None = None,
                   max_attempts: int | None = None,
                   stream_timeout: float | None = None,
                   retry_backoff_s: float | None = None,
                   service_config=None,
                   on_service=None) -> float:
    """Run the given streams concurrently; returns elapsed seconds.

    Elapsed is max(stream Power End) - min(stream Power Start) over the
    written time logs, the reference's definition (nds_bench.py:138-157).

    mode "service" multiplexes every stream through ONE shared
    admission-controlled QueryService over a single Session (shared
    program cache + compatible-plan batching across streams); per-stream
    time logs keep the same contract, but ``output_prefix`` (per-query
    parquet dumps) is not supported there.

    Streams run SUPERVISED: each gets ``max_attempts`` spawns (default
    EngineConfig.stream_attempts) and a ``stream_timeout`` wall budget
    (default EngineConfig.stream_timeout_s; 0 = none). A crashed or
    killed stream restarts with deterministic backoff; per-stream
    outcomes are written to ``throughput_status.csv`` in the log dir.
    Permanent failures raise ThroughputError carrying the partial elapsed
    over the completed streams.

    ``service_config`` (service mode only) overrides the round's
    ServiceConfig — the lifecycle's chaos rounds arm the self-healing
    knobs (circuit breaker, retry budget, lane watchdog) through it.
    ``on_service`` (service mode only) is called with the LIVE
    QueryService after start: the hook chaos/lifecycle instrumentation
    uses to observe or arm a round while its clients are in flight.
    """
    from .config import EngineConfig

    config = EngineConfig.from_property_file(property_file)
    if config.fault_points:
        # the supervisor's own fault points (stream.spawn) arm here: no
        # Session exists in the parent process to install them
        FAULTS.configure(config.fault_points)
    if max_attempts is None:
        max_attempts = max(1, config.stream_attempts)
    if stream_timeout is None:
        stream_timeout = config.stream_timeout_s or None
    if retry_backoff_s is None:
        retry_backoff_s = config.retry_backoff_s

    os.makedirs(time_log_dir, exist_ok=True)
    jobs = []
    for s in streams:
        stream_file = os.path.join(stream_dir, f"query_{s}.sql")
        log = stream_log_path(time_log_dir, s)
        out = os.path.join(output_prefix, f"stream_{s}") \
            if output_prefix else None
        jobs.append((s, stream_file, log, out))

    if mode == "process":
        proc_jobs = [(s, _stream_cmd(input_prefix, sf, log, input_format,
                                     out, json_summary_folder, sub_queries,
                                     property_file, backend, warmup, decimal))
                     for s, sf, log, out in jobs]
        statuses = supervise_processes(proc_jobs, max_attempts=max_attempts,
                                       stream_timeout=stream_timeout,
                                       backoff_s=retry_backoff_s)
    elif mode == "service":
        # in-process multi-tenant mode: ONE session + warehouse
        # registration + program cache serves every stream through the
        # admission-controlled service; streams are client threads
        from .config import apply_decimal, maybe_enable_compile_cache
        from .engine import Session
        from .service import QueryService, ServiceConfig

        maybe_enable_compile_cache()
        apply_decimal(config, decimal)
        session = Session(config)
        from .power import setup_tables
        setup_tables(session, input_prefix, input_format)
        svc_cfg = service_config if service_config is not None \
            else ServiceConfig(
                max_pending=max(256, 8 * len(jobs)),
                tenant_deadlines={}, default_deadline_s=0.0)
        with QueryService(session, svc_cfg) as service:
            if on_service is not None:
                on_service(service)
            def make_run(sid, sf, log, out):
                def run():
                    # one tenant per stream: the registry's per-tenant
                    # service_latency_ms series decompose the round
                    _run_stream_service(service, sf, log,
                                        sub_queries=sub_queries,
                                        warmup=warmup, backend=backend,
                                        tenant=f"stream{sid}")
                return run

            with ThreadPoolExecutor(max_workers=len(jobs)) as pool:
                futures = [pool.submit(_supervised_thread_stream, s,
                                       make_run(s, sf, log, out),
                                       max_attempts,
                                       stream_timeout, retry_backoff_s)
                           for s, sf, log, out in jobs]
                statuses = [f.result() for f in futures]
        _write_service_obs(time_log_dir)
    else:
        def make_run(sf, log, out):
            def run():
                _run_stream_thread(
                    input_prefix, sf, log, input_format=input_format,
                    output_prefix=out,
                    json_summary_folder=json_summary_folder,
                    sub_queries=sub_queries, property_file=property_file,
                    backend=backend, warmup=warmup, decimal=decimal)
            return run

        with ThreadPoolExecutor(max_workers=len(jobs)) as pool:
            futures = [pool.submit(_supervised_thread_stream, s,
                                   make_run(sf, log, out), max_attempts,
                                   stream_timeout, retry_backoff_s)
                       for s, sf, log, out in jobs]
            statuses = [f.result() for f in futures]

    write_status_csv(status_csv_path(time_log_dir), statuses)
    failed = sorted(s.stream for s in statuses if s.status != "Completed")
    logs = [log for _, _, log, _ in jobs]
    if failed:
        ok_logs = [stream_log_path(time_log_dir, s.stream)
                   for s in statuses if s.status == "Completed"]
        partial = throughput_elapsed(ok_logs, allow_partial=True) \
            if ok_logs else None
        detail = "; ".join(
            f"stream {s.stream}: {s.status} after {s.attempts} attempt(s)"
            f" ({s.error})" for s in statuses if s.status != "Completed")
        msg = f"throughput streams failed permanently: {detail}"
        if partial is not None:
            msg += (f"; partial elapsed over {len(ok_logs)} completed "
                    f"stream(s): {partial:.3f}s")
        raise ThroughputError(msg, partial_elapsed=partial, failed=failed)
    return throughput_elapsed(logs)


def scrape_log(time_log: str, strict: bool = True) -> tuple[int, int] | None:
    """Return (power start ms, power end ms) from a power-run time log.

    strict=False returns None instead of raising when the log lacks its
    sentinel rows (an interrupted stream) — throughput_elapsed uses it to
    name every incomplete stream at once."""
    start = end = None
    with open(time_log) as f:
        for row in csv.reader(f):
            if not row:
                continue
            if row[0] == "Power Start Time":
                start = int(row[1])
            elif row[0] == "Power End Time":
                end = int(row[1])
    if start is None or end is None:
        if strict:
            raise IncompleteStreamLog(
                f"{time_log} is missing its Power Start/End sentinel rows "
                "— the stream was interrupted before completing")
        return None
    return start, end


def throughput_elapsed(time_logs: list[str],
                       allow_partial: bool = False) -> float:
    """max(end) - min(start) in seconds over the stream logs.

    Incomplete logs (missing file or missing sentinel rows) raise one
    IncompleteStreamLog naming every affected stream; allow_partial=True
    computes over the complete logs instead (partial-elapsed reporting for
    supervised rounds with failed streams)."""
    spans = []
    incomplete = []
    for p in time_logs:
        if not os.path.exists(p):
            incomplete.append(f"{p} (missing)")
            continue
        span = scrape_log(p, strict=False)
        if span is None:
            incomplete.append(f"{p} (no sentinel rows — interrupted)")
            continue
        spans.append(span)
    if incomplete and not allow_partial:
        raise IncompleteStreamLog(
            "incomplete stream logs: " + "; ".join(incomplete))
    if not spans:
        raise IncompleteStreamLog(
            "no complete stream logs to compute elapsed from: "
            + "; ".join(incomplete))
    return (max(e for _, e in spans) - min(s for s, _ in spans)) / 1000.0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="nds_tpu.throughput")
    p.add_argument("input_prefix")
    p.add_argument("stream_dir")
    p.add_argument("streams", help="comma-separated stream ids, e.g. 1,2,3,4")
    p.add_argument("time_log_dir")
    p.add_argument("--input_format", default="parquet")
    p.add_argument("--output_prefix", default=None)
    p.add_argument("--json_summary_folder", default=None)
    p.add_argument("--sub_queries", default=None)
    p.add_argument("--property_file", default=None)
    p.add_argument("--backend", default=None, choices=["jax", "numpy"])
    p.add_argument("--mode", default="process",
                   choices=["process", "thread", "service"],
                   help="process = one OS process per stream (reference "
                        "shape); thread = in-process sessions; service = "
                        "all streams through one shared admission-"
                        "controlled QueryService (nds_tpu/service)")
    p.add_argument("--warmup", type=int, default=0,
                   help="untimed pre-runs per query in each stream")
    p.add_argument("--decimal", default=None, choices=["f64", "i64"])
    p.add_argument("--max_attempts", type=int, default=None,
                   help="spawn attempts per stream (restart on crash/kill)")
    p.add_argument("--stream_timeout", type=float, default=None,
                   help="per-stream wall-clock budget in seconds")
    a = p.parse_args(argv)
    ids = [int(s) for s in a.streams.split(",")]
    sub = a.sub_queries.split(",") if a.sub_queries else None
    elapsed = run_throughput(a.input_prefix, a.stream_dir, ids,
                             a.time_log_dir, a.input_format, a.output_prefix,
                             a.json_summary_folder, sub, a.property_file,
                             a.backend, a.mode, a.warmup, a.decimal,
                             max_attempts=a.max_attempts,
                             stream_timeout=a.stream_timeout)
    print(f"Throughput Test Time: {elapsed:.3f} seconds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
