"""Whole-process benchmark orchestrator: YAML-driven phases + primary metric.

Capability parity with the reference orchestrator (reference
nds/nds_bench.py): run steps 0-7 with per-step ``skip`` flags (bench.yml:
8-40), scrape report files for times and the load-end RNGSEED (:60-123),
split streams into halves for the two throughput/maintenance rounds
(get_stream_range :126-135), throughput elapsed = max(end)-min(start) over
stream logs (:138-157), maintenance = sum of refresh times (:176-196),
round every elapsed up to 0.1 s (:207-208), and compute the primary metric
``SF * (Sq*99) / (Tpt*Ttt*Tdm*Tld)^(1/4)`` in decimal hours with
Tpt=Tpower*Sq and Tld=0.01*Sq*Tload (get_perf_metric :334-357), writing
metrics.csv (:360-364).

Differences by design: phases run in-process (no subprocess/file contract
needed between layers), and the config is one YAML with per-phase
sections instead of the template zoo.
"""
from __future__ import annotations

import argparse
import csv
import math
import os
import sys

import yaml

from . import datagen, maintenance, streams, transcode
from .power import run_query_stream
from .resilience import RetryPolicy
from .throughput import run_throughput, stream_log_path, throughput_elapsed


def round_up_tenth(seconds: float) -> float:
    """Round an elapsed time up to the nearest 0.1 s (nds_bench.py:207)."""
    return math.ceil(seconds * 10.0) / 10.0


def get_stream_range(num_streams: int, first_or_second: int) -> list[int]:
    """Stream ids for throughput/maintenance round 1 or 2.

    Stream 0 is the power stream; rounds split the rest in half
    (nds_bench.py:126-135). num_streams must be odd and >= 3.
    """
    if num_streams < 3 or num_streams % 2 == 0:
        raise ValueError("num_streams must be an odd number >= 3")
    half = num_streams // 2
    if first_or_second == 1:
        return list(range(1, half + 1))
    return list(range(half + 1, num_streams))


def get_load_time(report_path: str) -> float:
    _require_report(report_path, "load_test")
    with open(report_path) as f:
        for line in f:
            if line.startswith("Load Test Time:"):
                return float(line.split(":")[1].split()[0])
    raise ValueError(f"no Load Test Time in {report_path}")


def get_load_end_timestamp(report_path: str) -> int:
    """RNGSEED scraped from the load report (nds_bench.py:60-76)."""
    _require_report(report_path, "load_test")
    with open(report_path) as f:
        for line in f:
            if line.startswith("RNGSEED used:"):
                return int(line.split(":")[1].strip().replace(" ", ""))
    raise ValueError(f"no RNGSEED in {report_path}")


def _require_report(path: str, phase: str):
    """Clear failure when a skipped phase's report is absent: skip means
    'already ran' (restartable split runs, reference bench.yml skip flags) —
    point the config at the prior run's report_dir or unskip the phase."""
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{phase} report {path!r} is missing: the phase was skipped but "
            f"never ran — unskip it or reuse a report_dir that has it")


def get_power_time(time_log: str) -> float:
    _require_report(time_log, "power_test")
    with open(time_log) as f:
        for row in csv.reader(f):
            if row and row[0] == "Power Test Time":
                return int(row[3]) / 1000.0
    raise ValueError(f"no Power Test Time in {time_log}")


def get_maintenance_time(time_log: str) -> float:
    """Sum of refresh-function times, seconds (nds_bench.py:176-196)."""
    _require_report(time_log, "maintenance_test")
    total_ms = 0
    seen = False
    with open(time_log) as f:
        for row in csv.reader(f):
            if not row or row[0] in ("query",) or row[0].startswith(
                    "Maintenance"):
                continue
            total_ms += int(row[3])
            seen = True
    if not seen:
        raise ValueError(f"no refresh rows in {time_log}")
    return total_ms / 1000.0


def get_perf_metric(scale_factor: float, num_streams: int, t_load: float,
                    t_power: float, t_tt1: float, t_tt2: float,
                    t_dm1: float, t_dm2: float) -> float:
    """Primary NDS metric (nds_bench.py:334-357).

    All t_* in seconds; internally converted to decimal hours. Sq is the
    per-round stream count (num_streams // 2).
    """
    sq = num_streams // 2
    to_hours = 1.0 / 3600.0
    t_ld = 0.01 * sq * t_load * to_hours
    t_pt = t_power * sq * to_hours
    t_tt = (t_tt1 + t_tt2) * to_hours
    t_dm = (t_dm1 + t_dm2) * to_hours
    denom = (t_pt * t_tt * t_dm * t_ld) ** 0.25
    return math.floor(scale_factor * (sq * 99) / denom)


def write_metrics_report(path: str, rows: list[list]) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", newline="") as f:
        csv.writer(f).writerows(rows)


def _skip(section: dict) -> bool:
    return bool(section.get("skip", False))


def run_full_bench(cfg: dict) -> dict:
    """Run every phase per the YAML config; returns the collected times."""
    from .config import maybe_enable_compile_cache

    maybe_enable_compile_cache()
    sf = float(cfg["data_gen"]["scale_factor"])
    num_streams = int(cfg["generate_query_stream"]["num_streams"])
    sq = num_streams // 2
    data_path = cfg["data_gen"]["data_path"]
    warehouse = cfg["load_test"]["warehouse_path"]
    stream_dir = cfg["generate_query_stream"]["stream_path"]
    report_dir = cfg.get("report_dir", "./nds_report")
    backend = cfg.get("backend")
    decimal = cfg.get("decimal")
    if decimal and decimal not in ("f64", "i64"):
        raise ValueError(f"bench config: unknown decimal {decimal!r} "
                         "(expected f64 or i64)")
    if decimal == "i64" and not cfg["load_test"].get("use_decimal", False):
        raise ValueError(
            "bench config: decimal: i64 requires load_test.use_decimal: true"
            " — an f64-loaded warehouse has no decimal columns to bind, so"
            " the run would silently measure f64")
    sub_queries = cfg.get("sub_queries")
    input_format = cfg["load_test"].get("format", "parquet")

    # step 0: data generation — source set + one refresh set per non-power
    # stream (reference run_data_gen generates the update sets the two
    # maintenance rounds consume, nds_bench.py:211-229)
    gen_cfg = cfg["data_gen"]
    if not _skip(gen_cfg):
        parallel = int(gen_cfg.get("parallel", 2))
        datagen.generate_data_local(data_path, sf, parallel, overwrite=True)
        for s in range(1, num_streams):
            datagen.generate_data_local(_refresh_dir(data_path, s), sf,
                                        parallel, update=s, overwrite=True)

    # step 1: load test (transcode into the warehouse)
    load_cfg = cfg["load_test"]
    load_report = os.path.join(report_dir, "load_report.txt")
    if not _skip(load_cfg):
        transcode.transcode(data_path, warehouse, load_report,
                            use_decimal=load_cfg.get("use_decimal", False))
    t_load = get_load_time(load_report)

    # step 2: query streams seeded by the load end timestamp
    qs_cfg = cfg["generate_query_stream"]
    if not _skip(qs_cfg):
        rngseed = qs_cfg.get("rngseed")
        if rngseed is None:  # an explicit seed of 0 must be honored
            rngseed = get_load_end_timestamp(load_report)
        streams.generate_query_streams(stream_dir, streams=num_streams,
                                       rngseed=int(rngseed))

    # step 3: power test = stream 0, serial
    power_cfg = cfg.get("power_test", {})
    power_log = os.path.join(report_dir, "power.csv")
    if not _skip(power_cfg):
        run_query_stream(warehouse, os.path.join(stream_dir, "query_0.sql"),
                         power_log, input_format=input_format,
                         output_prefix=power_cfg.get("output_prefix"),
                         json_summary_folder=power_cfg.get(
                             "json_summary_folder"),
                         sub_queries=sub_queries,
                         property_file=power_cfg.get("property_file"),
                         backend=backend, decimal=decimal,
                         warmup=int(power_cfg.get("warmup", 0)))
    t_power = get_power_time(power_log)

    # steps 4+6: throughput rounds; steps 5+7: maintenance rounds.
    # Phase-level retry (resilience: {phase_attempts: N, phase_backoff_s}):
    # a round that fails transiently — a permanently failed stream, a
    # dropped device tunnel — re-runs whole up to N times with backoff
    # before the bench aborts. Stream logs are rewritten per attempt, so a
    # retried round scrapes only its own successful run.
    res_cfg = cfg.get("resilience", {})
    phase_policy = RetryPolicy(
        max_attempts=max(1, int(res_cfg.get("phase_attempts", 1))),
        backoff_s=float(res_cfg.get("phase_backoff_s", 1.0)))
    tt_cfg = cfg.get("throughput_test", {})
    dm_cfg = cfg.get("maintenance_test", {})
    t_tt: dict[int, float] = {}
    t_dm: dict[int, float] = {}
    for rnd in (1, 2):
        ids = get_stream_range(num_streams, rnd)
        if not _skip(tt_cfg):
            phase_policy.call(
                run_throughput, warehouse, stream_dir, ids, report_dir,
                label=f"throughput round {rnd}",
                input_format=input_format,
                sub_queries=sub_queries, backend=backend,
                mode=tt_cfg.get("mode", "process"),
                warmup=int(tt_cfg.get("warmup", 0)),
                decimal=decimal,
                max_attempts=tt_cfg.get("stream_attempts"),
                stream_timeout=tt_cfg.get("stream_timeout"))
        tt_logs = [stream_log_path(report_dir, s) for s in ids]
        for lg in tt_logs:
            _require_report(lg, "throughput_test")
        t_tt[rnd] = throughput_elapsed(tt_logs)
        dm_total = 0.0
        for s in ids:
            dm_log = os.path.join(report_dir, f"maintenance_{s}.csv")
            if not _skip(dm_cfg):
                phase_policy.call(
                    maintenance.run_maintenance,
                    warehouse, _refresh_dir(data_path, s), dm_log,
                    label=f"maintenance stream {s}",
                    backend=backend, decimal=decimal)
            dm_total += get_maintenance_time(dm_log)
        t_dm[rnd] = dm_total

    times = {
        "load": round_up_tenth(t_load),
        "power": round_up_tenth(t_power),
        "throughput1": round_up_tenth(t_tt[1]),
        "throughput2": round_up_tenth(t_tt[2]),
        "maintenance1": round_up_tenth(t_dm[1]),
        "maintenance2": round_up_tenth(t_dm[2]),
    }
    metric = get_perf_metric(sf, num_streams, times["load"], times["power"],
                             times["throughput1"], times["throughput2"],
                             times["maintenance1"], times["maintenance2"])
    rows = [["scale_factor", sf], ["num_streams", num_streams], ["Sq", sq]]
    rows += [[k, v] for k, v in times.items()]
    rows.append(["perf_metric", metric])
    write_metrics_report(cfg.get("metrics_path",
                                 os.path.join(report_dir, "metrics.csv")),
                         rows)
    return {**times, "metric": metric}


def _refresh_dir(data_path: str, stream: int) -> str:
    return f"{data_path.rstrip('/')}_update_{stream}"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="nds_tpu.bench")
    p.add_argument("yaml_config")
    a = p.parse_args(argv)
    with open(a.yaml_config) as f:
        cfg = yaml.safe_load(f)
    result = run_full_bench(cfg)
    print(f"perf metric: {result['metric']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
