"""Preflight checks for the benchmark CLIs.

Capability parity with the reference's check module (reference
nds/check.py): python-version gate (:38-44), built-artifact lookup
(check_build, :47-66), path/range validators (:69-123), directory sizing
(:126-134), non-empty json-summary-folder guard (:136-145) and query-subset
validation (:147-152).
"""
from __future__ import annotations

import os
import sys

from .datagen import check_build, valid_range  # noqa: F401  (parity re-export)


def check_version(min_version: tuple[int, int] = (3, 9)) -> None:
    """Abort on unsupported interpreters (reference check.py:38-44)."""
    if sys.version_info < min_version:
        raise RuntimeError(
            f"python >= {'.'.join(map(str, min_version))} required, "
            f"found {sys.version.split()[0]}")


def get_abs_path(path: str) -> str:
    """Expand a user path to absolute (reference check.py:69-75)."""
    return os.path.abspath(os.path.expanduser(path))


def get_dir_size(path: str) -> int:
    """Total bytes under a directory tree (reference check.py:126-134)."""
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            fp = os.path.join(root, f)
            if not os.path.islink(fp):
                total += os.path.getsize(fp)
    return total


def check_json_summary_folder(path: str | None) -> None:
    """Refuse to overwrite an existing non-empty summary folder (reference
    check.py:136-145 — stale summaries would poison downstream reporting)."""
    if not path:
        return
    if os.path.exists(path) and os.listdir(path):
        raise RuntimeError(
            f"json summary folder {path!r} exists and is not empty; "
            "remove it or choose another location")


def check_query_subset_exists(query_dict, sub_queries) -> bool:
    """Every requested sub-query must exist in the stream (reference
    check.py:147-152)."""
    import re

    names = set(query_dict)
    bases = {re.sub(r"_part[12]$", "", k) for k in names}
    for q in sub_queries or []:
        if q not in names and q not in bases:
            raise RuntimeError(f"sub query {q!r} is not in the query stream")
    return True
