// ndsdgen — native data generator for the NDS-TPU benchmark framework.
//
// Replaces the reference's L0/L1 native layer (TPC-DS dsdgen + Hadoop MR
// wrapper GenTable.java; see SURVEY.md §1): emits the 24 source tables and
// the 12 data-maintenance staging tables as pipe-delimited files with
// -scale/-parallel/-child/-update chunk semantics. Original counter-based
// design (see gen.h): chunking never changes content.
//
// Statistical caveat (documented divergence): value distributions are
// plausible and referentially consistent but not bit-identical to the TPC
// toolkit's; the query corpus in this repo binds its parameters against
// THIS generator's domains, so data+queries are self-consistent.
//
// Build: make   (g++ -O2, no dependencies)
// Usage: ndsdgen -scale SF -dir DIR [-parallel N] [-child I]
//                [-table NAME] [-update K]

#include "gen.h"
#include "schema_def.inc"

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

// ---------------------------------------------------------------------------
// scaling model
// ---------------------------------------------------------------------------

struct StepRow { double sf; double rows; };

// stepped dimension sizes at standard scale factors (log-interpolated
// between, clamped outside). Approximate TPC-DS growth curves.
struct StepTable { const char* name; StepRow pts[6]; };
static const StepTable STEP_TABLES[] = {
    {"customer",        {{1, 100000}, {10, 500000}, {100, 2000000},
                         {1000, 12000000}, {3000, 30000000}, {10000, 65000000}}},
    {"customer_address",{{1, 50000}, {10, 250000}, {100, 1000000},
                         {1000, 6000000}, {3000, 15000000}, {10000, 32500000}}},
    {"item",            {{1, 18000}, {10, 102000}, {100, 204000},
                         {1000, 300000}, {3000, 360000}, {10000, 402000}}},
    {"store",           {{1, 12}, {10, 102}, {100, 402},
                         {1000, 1002}, {3000, 1350}, {10000, 1500}}},
    {"warehouse",       {{1, 5}, {10, 10}, {100, 15},
                         {1000, 20}, {3000, 22}, {10000, 25}}},
    {"web_site",        {{1, 30}, {10, 42}, {100, 24},
                         {1000, 54}, {3000, 66}, {10000, 78}}},
    {"web_page",        {{1, 60}, {10, 200}, {100, 2040},
                         {1000, 3000}, {3000, 3600}, {10000, 4002}}},
    {"promotion",       {{1, 300}, {10, 500}, {100, 1000},
                         {1000, 1500}, {3000, 1800}, {10000, 2000}}},
    {"reason",          {{1, 35}, {10, 45}, {100, 55},
                         {1000, 65}, {3000, 67}, {10000, 70}}},
    {"call_center",     {{1, 6}, {10, 24}, {100, 30},
                         {1000, 42}, {3000, 48}, {10000, 54}}},
    {"catalog_page",    {{1, 11718}, {10, 12000}, {100, 20400},
                         {1000, 30000}, {3000, 36000}, {10000, 40000}}},
};

static int64_t step_rows(const StepTable& t, double sf) {
    const StepRow* p = t.pts;
    if (sf <= p[0].sf) {
        double r = p[0].rows * sf / p[0].sf;
        return r < 1 ? 1 : (int64_t)r;
    }
    for (int i = 0; i < 5; i++) {
        if (sf <= p[i + 1].sf) {
            double f = (std::log(sf) - std::log(p[i].sf)) /
                       (std::log(p[i + 1].sf) - std::log(p[i].sf));
            return (int64_t)(p[i].rows +
                             f * (p[i + 1].rows - p[i].rows));
        }
    }
    return (int64_t)p[5].rows;
}

// average line-items per order for the three sales channels
static const int SS_AVG_LINES = 12, CS_AVG_LINES = 9, WS_AVG_LINES = 12;
static const int64_t SS_ORDERS_SF1 = 240000, CS_ORDERS_SF1 = 160000,
                     WS_ORDERS_SF1 = 60000;

// sales date window: 1998-01-02 .. 2002-12-31 (5 years, matches the query
// corpus's parameter domains)
static const int64_t SALES_SK_LO =
    JULIAN_1900_01_02 + (days_from_civil(1998, 1, 2) - EPOCH_1900_01_02);
static const int64_t SALES_SK_HI =
    JULIAN_1900_01_02 + (days_from_civil(2002, 12, 31) - EPOCH_1900_01_02);

static double g_scale = 1.0;

static int64_t orders_of(const char* table) {
    if (!strcmp(table, "store_sales"))   return (int64_t)(SS_ORDERS_SF1 * g_scale) + 1;
    if (!strcmp(table, "catalog_sales")) return (int64_t)(CS_ORDERS_SF1 * g_scale) + 1;
    if (!strcmp(table, "web_sales"))     return (int64_t)(WS_ORDERS_SF1 * g_scale) + 1;
    return 0;
}

static int64_t row_count(const char* name, double sf) {
    if (!strcmp(name, "date_dim")) return DATE_DIM_ROWS;
    if (!strcmp(name, "time_dim")) return 86400;
    if (!strcmp(name, "customer_demographics")) return 1920800;
    if (!strcmp(name, "household_demographics")) return 7200;
    if (!strcmp(name, "income_band")) return 20;
    if (!strcmp(name, "ship_mode")) return 20;
    for (const auto& t : STEP_TABLES)
        if (!strcmp(name, t.name)) return step_rows(t, sf);
    return 0;  // order-structured / derived tables sized elsewhere
}

static const TableDef* find_table(const char* name) {
    for (int i = 0; i < N_TABLES; i++)
        if (!strcmp(ALL_TABLES[i].name, name)) return &ALL_TABLES[i];
    return nullptr;
}

// ---------------------------------------------------------------------------
// field writer
// ---------------------------------------------------------------------------

struct Line {
    std::string buf;
    bool first = true;
    void sep() { if (!first) buf += '|'; first = false; }
    void null_() { sep(); }
    void i(int64_t v) { sep(); char t[24]; snprintf(t, 24, "%lld", (long long)v); buf += t; }
    void s(const std::string& v) { sep(); buf += v; }
    void cents(int64_t c) {  // decimal(x,2) from integer cents
        sep();
        char t[32];
        const char* sign = c < 0 ? "-" : "";
        int64_t a = c < 0 ? -c : c;
        snprintf(t, 32, "%s%lld.%02d", sign, (long long)(a / 100), (int)(a % 100));
        buf += t;
    }
    void date(int64_t epoch_days) {
        Civil c = civil_from_days(epoch_days);
        char t[24];
        snprintf(t, sizeof t, "%04d-%02d-%02d", c.y, c.m, c.d);
        s(t);
    }
    void end(FILE* f) { buf += '\n'; fwrite(buf.data(), 1, buf.size(), f); buf.clear(); first = true; }
};

// ---------------------------------------------------------------------------
// vocab pools
// ---------------------------------------------------------------------------

static const char* FIRST_NAMES[] = {"James","Mary","John","Patricia","Robert",
    "Jennifer","Michael","Linda","William","Elizabeth","David","Barbara",
    "Richard","Susan","Joseph","Jessica","Thomas","Sarah","Charles","Karen",
    "Daniel","Nancy","Matthew","Lisa","Anthony","Betty","Mark","Margaret",
    "Paul","Sandra","Steven","Ashley","Andrew","Kimberly","Kenneth","Emily",
    "Joshua","Donna","Kevin","Michelle"};
static const char* LAST_NAMES[] = {"Smith","Johnson","Williams","Brown",
    "Jones","Garcia","Miller","Davis","Rodriguez","Martinez","Hernandez",
    "Lopez","Gonzalez","Wilson","Anderson","Thomas","Taylor","Moore",
    "Jackson","Martin","Lee","Perez","Thompson","White","Harris","Sanchez",
    "Clark","Ramirez","Lewis","Robinson"};
static const char* CITIES[] = {"Fairview","Midway","Oak Grove","Five Points",
    "Pleasant Hill","Centerville","Riverside","Salem","Liberty","Greenville",
    "Union","Oakland","Spring Hill","Franklin","Clinton","Marion","Bethel",
    "Enterprise","Friendship","Glendale","Oakdale","Ashland","Antioch",
    "Concord","Lebanon","Springdale","Shiloh","Sunnyside","Mount Zion",
    "Pine Grove","Crossroads","Lakeview","Edgewood","Mount Pleasant",
    "Harmony","Highland Park","Woodville","Plainview","Unionville","Newport"};
static const char* COUNTIES[] = {"Williamson County","Walker County",
    "Ziebach County","Daviess County","Barrow County","Franklin Parish",
    "Luce County","Richland County","Furnas County","Maverick County",
    "Huron County","Kittitas County","Mobile County","Salem County",
    "Terrell County","Dauphin County","San Miguel County","Mesa County",
    "Lunenburg County","Perry County"};
static const char* STATES[] = {"AL","AK","AZ","AR","CA","CO","CT","DE","FL",
    "GA","HI","ID","IL","IN","IA","KS","KY","LA","ME","MD","MA","MI","MN",
    "MS","MO","MT","NE","NV","NH","NJ","NM","NY","NC","ND","OH","OK","OR",
    "PA","RI","SC","SD","TN","TX","UT","VT","VA","WA","WV","WI","WY"};
static const char* COUNTRIES[] = {"United States"};
static const char* STREET_NAMES[] = {"Main","Oak","Park","Elm","Maple",
    "Washington","Lake","Hill","Walnut","Spring","North","Ridge","Church",
    "Willow","Mill","Sunset","Railroad","Jackson","River","Highland","Cedar",
    "Valley","Chestnut","Green","Franklin","Johnson","Meadow","Forest",
    "College","Smith","Fourth","Third","Second","First","Sixth","Seventh",
    "Pine","Dogwood","Hickory","Poplar","Laurel","Locust","Birch","Center",
    "Davis","Wilson","Adams","Jefferson","Lincoln","Broadway"};
static const char* STREET_TYPES[] = {"Street","Avenue","Boulevard","Circle",
    "Court","Drive","Lane","Parkway","Place","Road","Way","Wy","ST","Ave",
    "Blvd","Cir","Ct","Dr","Ln","Pkwy"};
static const char* CATEGORIES[] = {"Books","Children","Electronics","Home",
    "Jewelry","Men","Music","Shoes","Sports","Women"};
static const char* CLASSES[] = {"accent","accessories","archery","arts",
    "athletic","audio","automotive","baseball","basketball","bathroom",
    "bedding","birdal","blinds/shades","bracelets","business","camcorders",
    "cameras","camping","classical","computers","consignment","cooking",
    "country","curtains/drapes","custom","decor","diamonds","disk drives",
    "dresses","dvd/vcr players","earings","entertainments","estate",
    "fiction","fishing","fitness","flatware","football","fragrances",
    "furniture","glassware","gold","golf","guns","history","hockey",
    "home repair","infants","jewelry boxes","karoke","kids","lighting",
    "loose stones","maternity","mattresses","memory","mens","mens watch",
    "monitors","musical","mystery","newborn","optics","outdoor","paint",
    "pants","parenting","pendants","personal","pools","pop","portable",
    "reference","rings","rock","romance","rugs","sailing","scanners",
    "school-uniforms","self-help","semi-precious","shirts","sports",
    "sports-apparel","stereo","swimwear","tables","televisions","tennis",
    "toddlers","travel","wallpaper","wireless","womens","womens watch"};
static const char* COLORS[] = {"almond","antique","aquamarine","azure",
    "beige","bisque","black","blanched","blue","blush","brown","burlywood",
    "burnished","chartreuse","chiffon","chocolate","coral","cornflower",
    "cornsilk","cream","cyan","dark","deep","dim","dodger","drab","firebrick",
    "floral","forest","frosted","gainsboro","ghost","goldenrod","green",
    "grey","honeydew","hot","indian","ivory","khaki","lace","lavender",
    "lawn","lemon","light","lime","linen","magenta","maroon","medium",
    "metallic","midnight","mint","misty","moccasin","navajo","navy","olive",
    "orange","orchid","pale","papaya","peach","peru","pink","plum","powder",
    "puff","purple","red","rose","rosy","royal","saddle","salmon","sandy",
    "seashell","sienna","sky","slate","smoke","snow","spring","steel","tan",
    "thistle","tomato","turquoise","violet","wheat","white","yellow"};
static const char* UNITS[] = {"Unknown","Each","Dozen","Case","Pallet","Gross",
    "Oz","Lb","Ton","Bundle","Box","Carton","Cup","Dram","Gram","Pound",
    "Ounce","Tbl","Tsp","Bunch"};
static const char* SIZES[] = {"small","medium","large","extra large","N/A",
    "economy","petite"};
static const char* BUY_POTENTIAL[] = {">10000","5001-10000","1001-5000",
    "501-1000","0-500","Unknown"};
static const char* EDUCATION[] = {"Primary","Secondary","College","2 yr Degree",
    "4 yr Degree","Advanced Degree","Unknown"};
static const char* CREDIT_RATING[] = {"Low Risk","Good","High Risk","Unknown"};
static const char* SALUTATIONS[] = {"Mr.","Mrs.","Ms.","Dr.","Miss","Sir"};
static const char* MEALS[] = {"breakfast","lunch","dinner",""};
static const char* SHIFTS[] = {"first","second","third"};
static const char* SM_TYPES[] = {"EXPRESS","NEXT DAY","OVERNIGHT","REGULAR","TWO DAY"};
static const char* SM_CARRIERS[] = {"UPS","FEDEX","AIRBORNE","USPS","DHL",
    "TBS","ZHOU","ZOUROS","MSC","LATVIAN","ALLIANCE","GREAT EASTERN",
    "DIAMOND","RUPEKSA","ORIENTAL","BARIAN","BOXBUNDLES","GERMA","HARMSTORF","PRIVATECARRIER"};
// digit syllables (TPC-DS-style number words) — store names and the like
static const char* SYLLABLES[] = {"ought","able","pri","ese","anti","cally",
    "ation","eing","n st","bar"};
static const char* WORDS[] = {"as","his","with","have","from","they","been",
    "about","important","results","right","different","general","good",
    "small","large","national","young","early","possible","social","still",
    "local","sure","particular","international","special","difficult",
    "available","likely","necessary","significant","recent","major","areas",
    "things","systems","services","problems","groups","companies","members",
    "countries","students","conditions","interests"};

#define POOL(r, P) P[(r) % (sizeof(P) / sizeof(P[0]))]

static std::string char16_id(uint64_t v) {
    char out[17];
    for (int i = 15; i >= 0; i--) { out[i] = 'A' + (int)(v % 26); v /= 26; }
    out[16] = 0;
    return out;
}

static std::string words_text(uint64_t r, int maxlen) {
    std::string s;
    int n = 3 + (int)(r % 8);
    for (int i = 0; i < n; i++) {
        const char* w = POOL(mix64(r + i), WORDS);
        if ((int)(s.size() + strlen(w) + 1) > maxlen) break;
        if (!s.empty()) s += ' ';
        s += w;
    }
    if (s.empty()) s = "able";
    return s;
}

// ---------------------------------------------------------------------------
// FK targets by column-name suffix
// ---------------------------------------------------------------------------

struct FkRule { const char* suffix; const char* target; };
static const FkRule FK_RULES[] = {
    {"_date_sk", "date_dim"}, {"_time_sk", "time_dim"},
    {"_item_sk", "item"}, {"_cdemo_sk", "customer_demographics"},
    {"_hdemo_sk", "household_demographics"}, {"_addr_sk", "customer_address"},
    {"_customer_sk", "customer"}, {"_store_sk", "store"},
    {"_promo_sk", "promotion"}, {"_reason_sk", "reason"},
    {"_warehouse_sk", "warehouse"}, {"_call_center_sk", "call_center"},
    {"_catalog_page_sk", "catalog_page"}, {"_ship_mode_sk", "ship_mode"},
    {"_web_page_sk", "web_page"}, {"_web_site_sk", "web_site"},
    {"_income_band_sk", "income_band"},
};

static bool ends_with(const char* s, const char* suf) {
    size_t ls = strlen(s), lf = strlen(suf);
    return ls >= lf && !strcmp(s + ls - lf, suf);
}

static int64_t fk_rows(const char* col) {
    for (const auto& r : FK_RULES)
        if (ends_with(col, r.suffix)) {
            if (!strcmp(r.target, "date_dim")) return -1;  // special: sales window
            return row_count(r.target, g_scale);
        }
    return 0;
}

// random sales-window date sk
static int64_t rnd_date_sk(uint64_t r) {
    return SALES_SK_LO + (int64_t)(r % (uint64_t)(SALES_SK_HI - SALES_SK_LO + 1));
}

// Ticket/order numbers are CHRONOLOGICAL (real retail numbering; also how
// a sequential OLTP source would emit them): the sold date is a monotone
// map of the order id over the sales date range, plus a few days of
// jitter. Date windows therefore correspond to contiguous ticket ranges,
// which is what lets per-file ticket [min,max] manifest stats prune the
// refresh deletes (warehouse file_stats; reference analog: Iceberg
// per-file column metrics, nds/nds_maintenance.py:146-185). The marginal
// date distribution stays uniform over the range.
static int64_t chrono_date_sk(int64_t order, int64_t n_orders, uint64_t r) {
    int64_t span = SALES_SK_HI - SALES_SK_LO + 1;
    int64_t base = SALES_SK_LO +
        (int64_t)(((__int128)order * span) / (n_orders > 0 ? n_orders : 1));
    int64_t d = base + (int64_t)(r % 7) - 3;
    if (d < SALES_SK_LO) d = SALES_SK_LO;
    if (d > SALES_SK_HI) d = SALES_SK_HI;
    return d;
}

// ---------------------------------------------------------------------------
// dedicated dimension generators
// ---------------------------------------------------------------------------

static const char* DAY_NAMES[] = {"Sunday","Monday","Tuesday","Wednesday",
    "Thursday","Friday","Saturday"};

static void gen_date_dim_row(int64_t row, Line& L, FILE* f) {
    int64_t sk = JULIAN_1900_01_02 + row;
    int64_t ed = sk_to_epoch_days(sk);
    Civil c = civil_from_days(ed);
    int dow = (int)(((ed % 7) + 11) % 7);  // 1970-01-01 is Thursday(4); Sunday=0
    int doy_jan1 = (int)(ed - days_from_civil(c.y, 1, 1));
    int qoy = (c.m - 1) / 3 + 1;
    int64_t months_since_1900 = (int64_t)(c.y - 1900) * 12 + (c.m - 1);
    int64_t week_seq = (ed - EPOCH_1900_01_02 + 1) / 7 + 1;
    L.i(sk);
    L.s(char16_id((uint64_t)sk));
    L.date(ed);
    L.i(months_since_1900);                    // d_month_seq
    L.i(week_seq);                             // d_week_seq
    L.i((int64_t)(c.y - 1900) * 4 + qoy - 1);  // d_quarter_seq
    L.i(c.y);
    L.i(dow);
    L.i(c.m);
    L.i(c.d);
    L.i(qoy);
    L.i(c.y);                                  // d_fy_year
    L.i((int64_t)(c.y - 1900) * 4 + qoy - 1);  // d_fy_quarter_seq
    L.i(week_seq);                             // d_fy_week_seq
    L.s(DAY_NAMES[dow]);
    { char q[16]; snprintf(q, sizeof q, "%04dQ%d", c.y, qoy); L.s(q); }  // d_quarter_name char(6)
    L.s((c.m == 12 && c.d == 25) || (c.m == 1 && c.d == 1) ||
        (c.m == 7 && c.d == 4) ? "Y" : "N");   // d_holiday
    L.s(dow == 0 || dow == 6 ? "Y" : "N");     // d_weekend
    L.s(((c.m == 12 && c.d == 26) || (c.m == 1 && c.d == 2) ||
         (c.m == 7 && c.d == 5)) ? "Y" : "N"); // d_following_holiday
    L.i(sk - c.d + 1);                         // d_first_dom
    {
        int ny = c.m == 12 ? c.y + 1 : c.y;
        int nm = c.m == 12 ? 1 : c.m + 1;
        int64_t last = days_from_civil(ny, nm, 1) - 1;
        L.i(JULIAN_1900_01_02 + (last - EPOCH_1900_01_02));  // d_last_dom
    }
    L.i(sk - 365);                             // d_same_day_ly
    L.i(sk - 91);                              // d_same_day_lq
    L.s("N"); L.s("N"); L.s("N"); L.s("N"); L.s("N");
    (void)doy_jan1;
    L.end(f);
}

static void gen_time_dim_row(int64_t row, Line& L, FILE* f) {
    int h = (int)(row / 3600), m = (int)((row / 60) % 60), s = (int)(row % 60);
    L.i(row);
    L.s(char16_id((uint64_t)row));
    L.i(row);
    L.i(h); L.i(m); L.i(s);
    L.s(h < 12 ? "AM" : "PM");
    L.s(SHIFTS[h / 8]);
    L.s(h / 8 == 0 ? (h < 4 ? "night" : "morning")
                   : h / 8 == 1 ? (h < 12 ? "morning" : "afternoon")
                                : (h < 20 ? "evening" : "night"));
    L.s(h >= 6 && h <= 9 ? "breakfast"
        : h >= 11 && h <= 13 ? "lunch"
        : h >= 17 && h <= 20 ? "dinner" : "");
    L.end(f);
}

static void gen_income_band_row(int64_t row, Line& L, FILE* f) {
    L.i(row + 1);
    L.i(row * 10000 + (row ? 1 : 0));
    L.i((row + 1) * 10000);
    L.end(f);
}

// ---------------------------------------------------------------------------
// generic rule-based column generator (dimensions + staging tables)
// ---------------------------------------------------------------------------

static uint64_t table_salt(const char* name) {
    uint64_t h = 1469598103934665603ull;
    for (const char* p = name; *p; p++) h = (h ^ (uint64_t)*p) * 1099511628211ull;
    return h;
}

static int g_update = 0;

// business-id (char16) of row `k` of a dimension table — the same formula
// generic_value uses for the dimension's own *_id column, so staging
// business-id references join back to real dimension rows
static std::string dim_business_id(const char* target, int64_t k) {
    return char16_id((uint64_t)k + table_salt(target) % 997);
}

struct IdRefRule { const char* suffix; const char* target; };
static const IdRefRule STAGING_ID_RULES[] = {
    {"_item_id", "item"}, {"_promotion_id", "promotion"},
    {"_store_id", "store"}, {"_customer_id", "customer"},
    {"_warehouse_id", "warehouse"}, {"_ship_mode_id", "ship_mode"},
    {"_shipmode_id", "ship_mode"}, {"_call_center_id", "call_center"},
    {"_web_site_id", "web_site"}, {"_web_page_id", "web_page"},
    {"_catalog_page_id", "catalog_page"}, {"_reason_id", "reason"},
};

// new refresh orders must not collide with base order numbers
static int64_t staging_order_base() {
    return 100000000LL + (int64_t)g_update * 10000000LL;
}

static bool is_null(uint64_t salt, int ci, int64_t row, const Col& c) {
    if (c.not_null) return false;
    return rng_at(salt, 0xA11ull * (ci + 1), (uint64_t)row) % 25 == 0;
}

static void generic_value(const TableDef& t, int ci, int64_t row,
                          uint64_t salt, Line& L) {
    const Col& c = t.cols[ci];
    uint64_t r = rng_at(salt, (uint64_t)ci + 1, (uint64_t)row);
    const char* n = c.name;
    // primary surrogate key: first column of every dimension
    if (ci == 0 && (c.kind == K_ID || c.kind == K_ID64)) { L.i(row + 1); return; }
    // staging (s_*) structural columns: order/lineitem alignment + id refs
    if (!strncmp(t.name, "s_", 2)) {
        if (!strcmp(n, "purc_purchase_id") || !strcmp(n, "cord_order_id") ||
            !strcmp(n, "word_order_id")) { L.i(staging_order_base() + row); return; }
        if (!strcmp(n, "plin_purchase_id")) { L.i(staging_order_base() + row / SS_AVG_LINES); return; }
        if (!strcmp(n, "plin_line_number")) { L.i(row % SS_AVG_LINES + 1); return; }
        if (!strcmp(n, "clin_order_id")) { L.i(staging_order_base() + row / CS_AVG_LINES); return; }
        if (!strcmp(n, "clin_line_number")) { L.i(row % CS_AVG_LINES + 1); return; }
        if (!strcmp(n, "wlin_order_id")) { L.i(staging_order_base() + row / WS_AVG_LINES); return; }
        if (!strcmp(n, "wlin_line_number")) { L.i(row % WS_AVG_LINES + 1); return; }
        if (!strcmp(n, "sret_ticket_number")) { L.i(1 + (int64_t)(r % (uint64_t)orders_of("store_sales"))); return; }
        if (!strcmp(n, "cret_order_id")) { L.i(1 + (int64_t)(r % (uint64_t)orders_of("catalog_sales"))); return; }
        if (!strcmp(n, "wret_order_id")) { L.i(1 + (int64_t)(r % (uint64_t)orders_of("web_sales"))); return; }
        if (!strcmp(n, "sret_purchase_id")) { L.i(1 + (int64_t)(r % (uint64_t)orders_of("store_sales"))); return; }
        if (!strcmp(n, "cret_line_number")) { L.i(1 + (int64_t)(r % CS_AVG_LINES)); return; }
        if (!strcmp(n, "wret_line_number")) { L.i(1 + (int64_t)(r % WS_AVG_LINES)); return; }
        if (!strcmp(n, "sret_line_number")) { L.i(1 + (int64_t)(r % SS_AVG_LINES)); return; }
        if (c.kind == K_STR && c.length == 16) {
            for (const auto& rule : STAGING_ID_RULES) {
                if (ends_with(n, rule.suffix)) {
                    if (!c.not_null && r % 25 == 0) { L.null_(); return; }
                    int64_t nrows = row_count(rule.target, g_scale);
                    L.s(dim_business_id(rule.target, (int64_t)(mix64(r) % (uint64_t)nrows)));
                    return;
                }
            }
        }
    }
    if (is_null(salt, ci, row, c)) { L.null_(); return; }
    if (c.kind == K_ID || c.kind == K_ID64) {
        int64_t nrows = fk_rows(n);
        if (nrows == -1) { L.i(rnd_date_sk(r)); return; }
        if (nrows > 0) { L.i(rng_range(r, 1, nrows)); return; }
        L.i(rng_range(r, 1, 1000));
        return;
    }
    if (c.kind == K_DATE) {
        if (ends_with(n, "rec_start_date")) { L.date(days_from_civil(1997 + (int)(row % 4), 1, 1)); return; }
        if (ends_with(n, "rec_end_date")) { L.null_(); return; }
        L.date(sk_to_epoch_days(rnd_date_sk(r)));
        return;
    }
    if (c.kind == K_DEC) {
        if (ends_with(n, "gmt_offset")) { L.cents(-500 - 100 * (int64_t)(r % 4)); return; }
        if (ends_with(n, "tax_percentage") || ends_with(n, "tax_precentage")) {
            L.cents((int64_t)(r % 12)); return;
        }
        if (!strcmp(n, "i_current_price")) { L.cents(9 + (int64_t)(r % 9991)); return; }
        if (!strcmp(n, "i_wholesale_cost")) {
            uint64_t r2 = rng_at(salt, (uint64_t)ci + 101, (uint64_t)row);
            L.cents(5 + (int64_t)(r2 % 6000)); return;
        }
        if (!strcmp(n, "p_cost")) { L.cents(100000); return; }
        L.cents((int64_t)(r % 10000));
        return;
    }
    if (c.kind == K_INT || c.kind == K_INT32) {
        if (ends_with(n, "_purchase_estimate")) { L.i(500 * (1 + (int64_t)(r % 20))); return; }
        if (ends_with(n, "_dep_count") || ends_with(n, "_vehicle_count")) { L.i((int64_t)(r % 7) - (ends_with(n, "_vehicle_count") ? 1 : 0)); return; }
        if (ends_with(n, "_dep_employed_count") || ends_with(n, "_dep_college_count")) { L.i((int64_t)(r % 7)); return; }
        if (ends_with(n, "birth_day")) { L.i(1 + (int64_t)(r % 28)); return; }
        if (ends_with(n, "birth_month")) { L.i(1 + (int64_t)(r % 12)); return; }
        if (ends_with(n, "birth_year")) { L.i(1924 + (int64_t)(r % 69)); return; }
        if (ends_with(n, "_brand_id")) { L.i(1001001 + (int64_t)(r % 1000) * 1001); return; }
        if (ends_with(n, "_class_id")) { L.i(1 + (int64_t)(r % 16)); return; }
        if (ends_with(n, "_category_id")) { L.i(1 + (int64_t)(r % 10)); return; }
        if (ends_with(n, "_manufact_id")) { L.i(1 + (int64_t)(r % 1000)); return; }
        if (ends_with(n, "_manager_id") || ends_with(n, "_mkt_id") ||
            ends_with(n, "_market_id")) { L.i(1 + (int64_t)(r % 100)); return; }
        if (ends_with(n, "_number_employees") || !strcmp(n, "cc_employees")) { L.i(200 + (int64_t)(r % 100)); return; }
        if (ends_with(n, "_floor_space") || ends_with(n, "_sq_ft")) { L.i(5000000 + (int64_t)(r % 5000000)); return; }
        if (ends_with(n, "_catalog_number")) { L.i(1 + (int64_t)(r % 20)); return; }
        if (ends_with(n, "_page_number")) { L.i(1 + (int64_t)(r % 200)); return; }
        if (ends_with(n, "_char_count")) { L.i(3000 + (int64_t)(r % 5000)); return; }
        if (ends_with(n, "_link_count") || ends_with(n, "_image_count")) { L.i(2 + (int64_t)(r % 23)); return; }
        if (ends_with(n, "_max_ad_count")) { L.i((int64_t)(r % 5)); return; }
        if (ends_with(n, "_response_target")) { L.i(1); return; }
        if (ends_with(n, "_division_id") || ends_with(n, "_company_id") ||
            !strcmp(n, "cc_division") || !strcmp(n, "cc_company")) { L.i(1 + (int64_t)(r % 6)); return; }
        if (ends_with(n, "_time")) { L.i((int64_t)(r % 86400)); return; }
        if (ends_with(n, "_qty_on_hand") ||
            ends_with(n, "quantity_on_hand")) { L.i((int64_t)(r % 1000)); return; }
        // order/lineitem quantities are <= 100 per spec; larger values
        // overflow DECIMAL(7,2) ext_* products in the LF_* insert views
        if (ends_with(n, "_quantity") || ends_with(n, "_qty")) { L.i(1 + (int64_t)(r % 100)); return; }
        L.i(1 + (int64_t)(r % 1000));
        return;
    }
    // strings
    if (ends_with(n, "_id") && c.length == 16) { L.s(char16_id((uint64_t)row + salt % 997)); return; }
    if (ends_with(n, "street_number")) { L.i(1 + (int64_t)(r % 1000)); return; }
    if (ends_with(n, "street_name")) {
        std::string v = POOL(r, STREET_NAMES);
        v += " "; v += POOL(mix64(r), STREET_NAMES);
        L.s(v); return;
    }
    if (ends_with(n, "street_type")) { L.s(POOL(r, STREET_TYPES)); return; }
    if (ends_with(n, "suite_number")) {
        char t2[16]; snprintf(t2, 16, "Suite %d", (int)(r % 100)); L.s(t2); return;
    }
    if (ends_with(n, "_city")) { L.s(POOL(r, CITIES)); return; }
    if (ends_with(n, "_county")) { L.s(POOL(r, COUNTIES)); return; }
    if (ends_with(n, "_state")) { L.s(POOL(r, STATES)); return; }
    if (ends_with(n, "_zip")) {
        char t2[8]; snprintf(t2, 8, "%05d", (int)(r % 100000)); L.s(t2); return;
    }
    if (ends_with(n, "_country")) { L.s(POOL(r, COUNTRIES)); return; }
    if (ends_with(n, "first_name")) { L.s(POOL(r, FIRST_NAMES)); return; }
    if (ends_with(n, "last_name")) { L.s(POOL(r, LAST_NAMES)); return; }
    if (ends_with(n, "_manager") || ends_with(n, "_market_manager")) {
        std::string v = POOL(r, FIRST_NAMES);
        v += " "; v += POOL(mix64(r), LAST_NAMES);
        L.s(v); return;
    }
    if (ends_with(n, "_salutation")) { L.s(POOL(r, SALUTATIONS)); return; }
    if (!strcmp(n, "cd_gender")) { L.s(r % 2 ? "M" : "F"); return; }
    if (!strcmp(n, "cd_marital_status")) { const char* MS[] = {"S","M","D","W","U"}; L.s(MS[r % 5]); return; }
    if (ends_with(n, "education_status")) { L.s(POOL(r, EDUCATION)); return; }
    if (ends_with(n, "credit_rating")) { L.s(POOL(r, CREDIT_RATING)); return; }
    if (ends_with(n, "buy_potential")) { L.s(POOL(r, BUY_POTENTIAL)); return; }
    if (!strcmp(n, "i_category")) { L.s(POOL(r, CATEGORIES)); return; }
    if (!strcmp(n, "i_class")) { L.s(POOL(r, CLASSES)); return; }
    if (!strcmp(n, "i_brand")) {
        char t2[64]; snprintf(t2, 64, "%sbrand #%d",
                              (r % 2) ? "corp" : "import", (int)(r % 10) + 1);
        L.s(t2); return;
    }
    if (!strcmp(n, "i_manufact")) {
        char t2[32]; snprintf(t2, 32, "manufact%d", (int)(r % 1000) + 1); L.s(t2); return;
    }
    if (!strcmp(n, "i_color")) { L.s(POOL(r, COLORS)); return; }
    if (!strcmp(n, "i_units")) { L.s(POOL(r, UNITS)); return; }
    if (!strcmp(n, "i_size")) { L.s(POOL(r, SIZES)); return; }
    if (!strcmp(n, "i_container")) { L.s("Unknown"); return; }
    if (!strcmp(n, "i_product_name")) { L.s(words_text(r, c.length ? c.length : 50)); return; }
    if (ends_with(n, "_carrier")) { L.s(POOL(r, SM_CARRIERS)); return; }
    if (!strcmp(n, "sm_type")) { L.s(POOL(r, SM_TYPES)); return; }
    if (!strcmp(n, "sm_code")) { const char* SC[] = {"AIR","SURFACE","SEA"}; L.s(SC[r % 3]); return; }
    if (ends_with(n, "_shift") || ends_with(n, "sub_shift")) { L.s(SHIFTS[r % 3]); return; }
    if (ends_with(n, "meal_time")) { L.s(MEALS[r % 4]); return; }
    if (ends_with(n, "_hours")) { const char* H[] = {"8AM-4PM","8AM-12AM","8AM-8AM"}; L.s(H[r % 3]); return; }
    if (ends_with(n, "day_name")) { L.s(DAY_NAMES[r % 7]); return; }
    if (ends_with(n, "_email_address")) {
        std::string v = POOL(r, FIRST_NAMES);
        v += "."; v += POOL(mix64(r), LAST_NAMES); v += "@example.com";
        L.s(v); return;
    }
    if (ends_with(n, "_login")) { L.null_(); return; }
    if (ends_with(n, "_url")) { L.s("http://www.foo.com"); return; }
    if (!strcmp(n, "s_store_name") || !strcmp(n, "w_warehouse_name")) {
        L.s(POOL(r, SYLLABLES)); return;
    }
    if (ends_with(n, "_company_name")) { L.s(POOL(r, SYLLABLES)); return; }
    if (ends_with(n, "_name") && c.length <= 60) {
        std::string v = POOL(r, WORDS); v += POOL(mix64(r), WORDS);
        L.s(v.substr(0, c.length ? c.length : 50)); return;
    }
    if (c.length == 1) { L.s(r % 2 ? "Y" : "N"); return; }
    if (ends_with(n, "_date")) {  // char(10) staging dates
        L.date(sk_to_epoch_days(rnd_date_sk(r))); return;
    }
    if (ends_with(n, "_time")) {  // char(10) staging time-of-day (seconds)
        L.i((int64_t)(r % 86400)); return;
    }
    L.s(words_text(r, c.length ? c.length : 60));
}

// ---------------------------------------------------------------------------
// sales / returns (order-structured), inventory
// ---------------------------------------------------------------------------

struct SaleLine {
    int64_t order, line, item, qty;
    int64_t wholesale, list, sales_price;       // cents, per-unit
    int64_t ext_discount, ext_sales, ext_wholesale, ext_list, ext_tax;
    int64_t coupon, ext_ship, net_paid, net_paid_tax, net_paid_ship,
            net_paid_ship_tax, net_profit;
    int64_t date_sk, time_sk, ship_date_sk, customer;
    bool returned;
    int64_t ret_qty;
};

static int order_lines(uint64_t salt, int64_t order, int avg) {
    return 1 + (int)(rng_at(salt, 0x11, (uint64_t)order) % (uint64_t)(2 * avg - 1));
}

static SaleLine make_line(uint64_t salt, int64_t order, int line,
                          int64_t n_orders) {
    SaleLine o;
    uint64_t ro = rng_at(salt, 0x22, (uint64_t)order);
    uint64_t rl = rng_at(salt, 0x33, (uint64_t)(order * 131 + line));
    o.order = order + 1;
    o.line = line + 1;
    o.item = 1 + (int64_t)(rl % (uint64_t)row_count("item", g_scale));
    o.qty = 1 + (int64_t)(mix64(rl + 1) % 100);
    o.wholesale = 100 + (int64_t)(mix64(rl + 2) % 9900);          // 1.00-99.99
    int markup = 10 + (int)(mix64(rl + 3) % 190);                  // 10%-200%
    o.list = o.wholesale * (100 + markup) / 100;
    int discount = (int)(mix64(rl + 4) % 100);                     // 0-99%
    o.sales_price = o.list * (100 - discount) / 100;
    o.ext_discount = o.qty * (o.list - o.sales_price);
    o.ext_sales = o.qty * o.sales_price;
    o.ext_wholesale = o.qty * o.wholesale;
    o.ext_list = o.qty * o.list;
    int tax_rate = (int)(mix64(rl + 5) % 10);                      // 0-9%
    o.ext_tax = o.ext_sales * tax_rate / 100;
    o.coupon = (mix64(rl + 6) % 5) ? 0 : o.ext_sales / 5;
    int64_t ship_unit = (int64_t)(mix64(rl + 7) % (uint64_t)(o.list / 2 + 1));
    o.ext_ship = o.qty * ship_unit;
    o.net_paid = o.ext_sales - o.coupon;
    o.net_paid_tax = o.net_paid + o.ext_tax;
    o.net_paid_ship = o.net_paid + o.ext_ship;
    o.net_paid_ship_tax = o.net_paid + o.ext_ship + o.ext_tax;
    o.net_profit = o.net_paid - o.ext_wholesale;
    o.date_sk = chrono_date_sk(order, n_orders, mix64(ro + 11));
    o.time_sk = (int64_t)(mix64(ro + 1) % 86400);
    o.ship_date_sk = o.date_sk + 2 + (int64_t)(mix64(ro + 2) % 119);
    o.customer = 1 + (int64_t)(mix64(ro + 3) % (uint64_t)row_count("customer", g_scale));
    o.returned = (mix64(rl + 8) % 10) == 0;
    o.ret_qty = 1 + (int64_t)(mix64(rl + 9) % (uint64_t)o.qty);
    return o;
}

// nullable FK with 1/25 null rate, keyed off the sale's rng
static void fk_or_null(Line& L, uint64_t r, const char* target) {
    if (r % 25 == 0) { L.null_(); return; }
    int64_t n = row_count(target, g_scale);
    L.i(1 + (int64_t)(mix64(r) % (uint64_t)n));
}

static void gen_store_sales_row(uint64_t salt, const SaleLine& o, Line& L, FILE* f) {
    uint64_t rx = rng_at(salt, 0x44, (uint64_t)(o.order * 131 + o.line));
    if (mix64(rx + 99) % 25 == 0) L.null_(); else L.i(o.date_sk);
    if (mix64(rx + 98) % 25 == 0) L.null_(); else L.i(o.time_sk);
    L.i(o.item);
    fk_or_null(L, rx + 1, "customer");
    fk_or_null(L, rx + 2, "customer_demographics");
    fk_or_null(L, rx + 3, "household_demographics");
    fk_or_null(L, rx + 4, "customer_address");
    fk_or_null(L, rx + 5, "store");
    fk_or_null(L, rx + 6, "promotion");
    L.i(o.order);
    L.i(o.qty);
    L.cents(o.wholesale); L.cents(o.list); L.cents(o.sales_price);
    L.cents(o.ext_discount); L.cents(o.ext_sales); L.cents(o.ext_wholesale);
    L.cents(o.ext_list); L.cents(o.ext_tax); L.cents(o.coupon);
    L.cents(o.net_paid); L.cents(o.net_paid_tax); L.cents(o.net_profit);
    L.end(f);
}

static void gen_store_returns_row(uint64_t salt, const SaleLine& o, Line& L, FILE* f) {
    uint64_t rr = rng_at(salt, 0x55, (uint64_t)(o.order * 131 + o.line));
    int64_t ret_date = o.date_sk + 1 + (int64_t)(rr % 90);
    int64_t amt = o.ret_qty * o.sales_price;
    int64_t tax = amt * 8 / 100;
    int64_t fee = 50 + (int64_t)(mix64(rr + 1) % 10000);
    int64_t ship = o.ret_qty * (o.ext_ship / (o.qty ? o.qty : 1));
    int64_t refunded = amt / 2;
    int64_t reversed = amt - refunded;
    L.i(ret_date);
    L.i((int64_t)(mix64(rr + 2) % 86400));
    L.i(o.item);
    fk_or_null(L, rr + 3, "customer");
    fk_or_null(L, rr + 4, "customer_demographics");
    fk_or_null(L, rr + 5, "household_demographics");
    fk_or_null(L, rr + 6, "customer_address");
    fk_or_null(L, rr + 7, "store");
    fk_or_null(L, rr + 8, "reason");
    L.i(o.order);
    L.i(o.ret_qty);
    L.cents(amt); L.cents(tax); L.cents(amt + tax); L.cents(fee);
    L.cents(ship); L.cents(refunded); L.cents(reversed); L.cents(0);
    L.cents(amt + fee + ship - refunded);
    L.end(f);
}

// catalog_sales / web_sales share a wide layout; generate via column walk
static void gen_channel_sales_row(const TableDef& t, uint64_t salt,
                                  const SaleLine& o, Line& L, FILE* f) {
    uint64_t rx = rng_at(salt, 0x66, (uint64_t)(o.order * 131 + o.line));
    int ci = 0;
    for (; ci < t.ncols; ci++) {
        const Col& c = t.cols[ci];
        const char* n = c.name;
        if (ends_with(n, "sold_date_sk")) { L.i(o.date_sk); continue; }
        if (ends_with(n, "sold_time_sk")) { L.i(o.time_sk); continue; }
        if (ends_with(n, "ship_date_sk")) { L.i(o.ship_date_sk); continue; }
        if (ends_with(n, "_item_sk")) { L.i(o.item); continue; }
        if (ends_with(n, "order_number")) { L.i(o.order); continue; }
        if (ends_with(n, "quantity")) { L.i(o.qty); continue; }
        if (ends_with(n, "bill_customer_sk") || ends_with(n, "ship_customer_sk")) {
            L.i(o.customer); continue;
        }
        if (ends_with(n, "wholesale_cost")) { L.cents(o.wholesale); continue; }
        if (ends_with(n, "list_price")) { L.cents(o.list); continue; }
        if (ends_with(n, "sales_price")) { L.cents(o.sales_price); continue; }
        if (ends_with(n, "ext_discount_amt")) { L.cents(o.ext_discount); continue; }
        if (ends_with(n, "ext_sales_price")) { L.cents(o.ext_sales); continue; }
        if (ends_with(n, "ext_wholesale_cost")) { L.cents(o.ext_wholesale); continue; }
        if (ends_with(n, "ext_list_price")) { L.cents(o.ext_list); continue; }
        if (ends_with(n, "ext_tax")) { L.cents(o.ext_tax); continue; }
        if (ends_with(n, "coupon_amt")) { L.cents(o.coupon); continue; }
        if (ends_with(n, "ext_ship_cost")) { L.cents(o.ext_ship); continue; }
        if (ends_with(n, "net_paid_inc_ship_tax")) { L.cents(o.net_paid_ship_tax); continue; }
        if (ends_with(n, "net_paid_inc_ship")) { L.cents(o.net_paid_ship); continue; }
        if (ends_with(n, "net_paid_inc_tax")) { L.cents(o.net_paid_tax); continue; }
        if (ends_with(n, "net_paid")) { L.cents(o.net_paid); continue; }
        if (ends_with(n, "net_profit")) { L.cents(o.net_profit); continue; }
        // remaining FK columns
        if (c.kind == K_ID) {
            uint64_t rc = mix64(rx + (uint64_t)ci);
            int64_t nrows = fk_rows(n);
            if (rc % 25 == 0 && !c.not_null) { L.null_(); continue; }
            if (nrows == -1) { L.i(rnd_date_sk(rc)); continue; }
            if (nrows > 0) { L.i(1 + (int64_t)(rc % (uint64_t)nrows)); continue; }
        }
        L.i(1);
    }
    L.end(f);
}

static void gen_channel_returns_row(const TableDef& t, uint64_t salt,
                                    const SaleLine& o, Line& L, FILE* f) {
    uint64_t rr = rng_at(salt, 0x77, (uint64_t)(o.order * 131 + o.line));
    int64_t ret_date = o.date_sk + 1 + (int64_t)(rr % 90);
    int64_t amt = o.ret_qty * o.sales_price;
    int64_t tax = amt * 8 / 100;
    int64_t fee = 50 + (int64_t)(mix64(rr + 1) % 10000);
    int64_t ship = o.ret_qty * (o.ext_ship / (o.qty ? o.qty : 1));
    int64_t refunded = amt / 2;
    for (int ci = 0; ci < t.ncols; ci++) {
        const Col& c = t.cols[ci];
        const char* n = c.name;
        if (ends_with(n, "returned_date_sk")) { L.i(ret_date); continue; }
        if (ends_with(n, "returned_time_sk")) { L.i((int64_t)(mix64(rr + 2) % 86400)); continue; }
        if (ends_with(n, "_item_sk")) { L.i(o.item); continue; }
        if (ends_with(n, "order_number")) { L.i(o.order); continue; }
        if (ends_with(n, "return_quantity")) { L.i(o.ret_qty); continue; }
        if (ends_with(n, "return_amount") || ends_with(n, "return_amt")) { L.cents(amt); continue; }
        if (ends_with(n, "return_tax")) { L.cents(tax); continue; }
        if (ends_with(n, "return_amt_inc_tax")) { L.cents(amt + tax); continue; }
        if (ends_with(n, "_fee")) { L.cents(fee); continue; }
        if (ends_with(n, "return_ship_cost")) { L.cents(ship); continue; }
        if (ends_with(n, "refunded_cash")) { L.cents(refunded); continue; }
        if (ends_with(n, "reversed_charge")) { L.cents(amt - refunded); continue; }
        if (ends_with(n, "store_credit") || ends_with(n, "account_credit") ||
            ends_with(n, "merchant_credit")) { L.cents(0); continue; }
        if (ends_with(n, "net_loss")) { L.cents(amt + fee + ship - refunded); continue; }
        if (ends_with(n, "customer_sk")) { L.i(o.customer); continue; }
        if (c.kind == K_ID) {
            uint64_t rc = mix64(rr + 10 + (uint64_t)ci);
            int64_t nrows = fk_rows(n);
            if (rc % 25 == 0 && !c.not_null) { L.null_(); continue; }
            if (nrows == -1) { L.i(rnd_date_sk(rc)); continue; }
            if (nrows > 0) { L.i(1 + (int64_t)(rc % (uint64_t)nrows)); continue; }
        }
        L.cents(0);
    }
    L.end(f);
}

// ---------------------------------------------------------------------------
// per-table generation entry
// ---------------------------------------------------------------------------

struct Chunk { int64_t lo, hi; };  // [lo, hi) in row or order space

static Chunk chunk_of(int64_t total, int parallel, int child) {
    int64_t lo = total * (child - 1) / parallel;
    int64_t hi = total * child / parallel;
    return {lo, hi};
}

static const char* sales_of_returns(const char* name) {
    if (!strcmp(name, "store_returns")) return "store_sales";
    if (!strcmp(name, "catalog_returns")) return "catalog_sales";
    if (!strcmp(name, "web_returns")) return "web_sales";
    return nullptr;
}

static int avg_lines_of(const char* sales) {
    if (!strcmp(sales, "store_sales")) return SS_AVG_LINES;
    if (!strcmp(sales, "catalog_sales")) return CS_AVG_LINES;
    return WS_AVG_LINES;
}

static void generate_table(const char* name, double sf, int parallel,
                           int child, int update, FILE* f) {
    g_scale = sf;
    g_update = update;
    const TableDef* t = find_table(name);
    Line L;
    uint64_t salt = table_salt(name) ^ (update ? mix64(0xDEADull + update) : 0);

    if (!strcmp(name, "dbgen_version")) {
        L.s("2.0.0-nds-tpu"); L.s("2026-01-01"); L.s("00:00:00"); L.s("ndsdgen");
        L.end(f);
        return;
    }
    if (!strcmp(name, "date_dim")) {
        Chunk c = chunk_of(DATE_DIM_ROWS, parallel, child);
        for (int64_t i = c.lo; i < c.hi; i++) gen_date_dim_row(i, L, f);
        return;
    }
    if (!strcmp(name, "time_dim")) {
        Chunk c = chunk_of(86400, parallel, child);
        for (int64_t i = c.lo; i < c.hi; i++) gen_time_dim_row(i, L, f);
        return;
    }
    if (!strcmp(name, "income_band")) {
        Chunk c = chunk_of(20, parallel, child);
        for (int64_t i = c.lo; i < c.hi; i++) gen_income_band_row(i, L, f);
        return;
    }
    if (!strcmp(name, "inventory")) {
        // weekly snapshots: 261 weeks x items x warehouses, (item+week) parity
        int64_t items = row_count("item", sf);
        int64_t whs = row_count("warehouse", sf);
        int64_t weeks = 261;
        Chunk c = chunk_of(weeks, parallel, child);
        for (int64_t w = c.lo; w < c.hi; w++) {
            int64_t date_sk = SALES_SK_LO + w * 7 - 1;
            for (int64_t it = 1 + (w % 2); it <= items; it += 2) {
                for (int64_t h = 1; h <= whs; h++) {
                    L.i(date_sk); L.i(it); L.i(h);
                    uint64_t r = rng_at(salt, (uint64_t)w, (uint64_t)(it * 131 + h));
                    if (r % 25 == 0) L.null_(); else L.i((int64_t)(r % 1000));
                    L.end(f);
                }
            }
        }
        return;
    }
    if (!strcmp(name, "store_sales") || !strcmp(name, "catalog_sales") ||
        !strcmp(name, "web_sales")) {
        int avg = avg_lines_of(name);
        int64_t orders = orders_of(name);
        Chunk c = chunk_of(orders, parallel, child);
        bool is_ss = !strcmp(name, "store_sales");
        for (int64_t o = c.lo; o < c.hi; o++) {
            int nlines = order_lines(salt, o, avg);
            for (int ln = 0; ln < nlines; ln++) {
                SaleLine s = make_line(salt, o, ln, orders);
                if (is_ss) gen_store_sales_row(salt, s, L, f);
                else gen_channel_sales_row(*t, salt, s, L, f);
            }
        }
        return;
    }
    if (const char* sales = sales_of_returns(name)) {
        uint64_t ssalt = table_salt(sales) ^ (update ? mix64(0xDEADull + update) : 0);
        int avg = avg_lines_of(sales);
        int64_t orders = orders_of(sales);
        Chunk c = chunk_of(orders, parallel, child);
        bool is_sr = !strcmp(name, "store_returns");
        for (int64_t o = c.lo; o < c.hi; o++) {
            int nlines = order_lines(ssalt, o, avg);
            for (int ln = 0; ln < nlines; ln++) {
                SaleLine s = make_line(ssalt, o, ln, orders);
                if (!s.returned) continue;
                if (is_sr) gen_store_returns_row(salt, s, L, f);
                else gen_channel_returns_row(*t, salt, s, L, f);
            }
        }
        return;
    }
    if (!strcmp(name, "delete") || !strcmp(name, "inventory_delete")) {
        // 3 date-range tuples per update set (reference nds_maintenance.py:75-96
        // substitutes DATE1/DATE2 from these)
        for (int i = 0; i < 3; i++) {
            int64_t base = SALES_SK_LO + 300 * (update ? update : 1) + 40 * i;
            L.date(sk_to_epoch_days(base));
            L.date(sk_to_epoch_days(base + 30));
            L.end(f);
        }
        return;
    }
    if (!t) { fprintf(stderr, "unknown table %s\n", name); exit(2); }

    // staging tables (s_*): sized off the parent channel's order count
    int64_t rows;
    if (!strncmp(name, "s_", 2)) {
        double frac = 0.001;  // refresh set ~0.1% of base orders per update
        if (!strcmp(name, "s_purchase")) rows = (int64_t)(SS_ORDERS_SF1 * sf * frac) + 10;
        else if (!strcmp(name, "s_purchase_lineitem")) rows = (int64_t)(SS_ORDERS_SF1 * sf * frac * SS_AVG_LINES) + 10;
        else if (!strcmp(name, "s_catalog_order")) rows = (int64_t)(CS_ORDERS_SF1 * sf * frac) + 10;
        else if (!strcmp(name, "s_catalog_order_lineitem")) rows = (int64_t)(CS_ORDERS_SF1 * sf * frac * CS_AVG_LINES) + 10;
        else if (!strcmp(name, "s_web_order")) rows = (int64_t)(WS_ORDERS_SF1 * sf * frac) + 10;
        else if (!strcmp(name, "s_web_order_lineitem")) rows = (int64_t)(WS_ORDERS_SF1 * sf * frac * WS_AVG_LINES) + 10;
        else if (!strcmp(name, "s_store_returns")) rows = (int64_t)(SS_ORDERS_SF1 * sf * frac * SS_AVG_LINES / 10) + 10;
        else if (!strcmp(name, "s_catalog_returns")) rows = (int64_t)(CS_ORDERS_SF1 * sf * frac * CS_AVG_LINES / 10) + 10;
        else if (!strcmp(name, "s_web_returns")) rows = (int64_t)(WS_ORDERS_SF1 * sf * frac * WS_AVG_LINES / 10) + 10;
        else if (!strcmp(name, "s_inventory")) rows = (int64_t)(row_count("item", sf)) + 10;
        else rows = 100;
    } else {
        rows = row_count(name, sf);
    }
    Chunk c = chunk_of(rows, parallel, child);
    for (int64_t i = c.lo; i < c.hi; i++) {
        for (int ci = 0; ci < t->ncols; ci++) generic_value(*t, ci, i, salt, L);
        L.end(f);
    }
}

// ---------------------------------------------------------------------------
// CLI
// ---------------------------------------------------------------------------

static const char* SOURCE_TABLES[] = {"call_center","catalog_page",
    "catalog_returns","catalog_sales","customer","customer_address",
    "customer_demographics","date_dim","dbgen_version",
    "household_demographics","income_band","inventory","item","promotion",
    "reason","ship_mode","store","store_returns","store_sales","time_dim",
    "warehouse","web_page","web_returns","web_sales","web_site"};
static const char* MAINT_TABLES[] = {"s_purchase_lineitem","s_purchase",
    "s_catalog_order","s_web_order","s_catalog_order_lineitem",
    "s_web_order_lineitem","s_store_returns","s_catalog_returns",
    "s_web_returns","s_inventory","delete","inventory_delete"};

int main(int argc, char** argv) {
    double sf = 1.0;
    int parallel = 1, child = 1, update = 0;
    const char* dir = ".";
    const char* only = nullptr;
    for (int i = 1; i < argc; i++) {
        if (!strcmp(argv[i], "-scale") && i + 1 < argc) sf = atof(argv[++i]);
        else if (!strcmp(argv[i], "-parallel") && i + 1 < argc) parallel = atoi(argv[++i]);
        else if (!strcmp(argv[i], "-child") && i + 1 < argc) child = atoi(argv[++i]);
        else if (!strcmp(argv[i], "-update") && i + 1 < argc) update = atoi(argv[++i]);
        else if (!strcmp(argv[i], "-dir") && i + 1 < argc) dir = argv[++i];
        else if (!strcmp(argv[i], "-table") && i + 1 < argc) only = argv[++i];
        else { fprintf(stderr, "usage: ndsdgen -scale SF -dir DIR [-parallel N]"
                               " [-child I] [-table NAME] [-update K]\n"); return 2; }
    }
    if (child < 1 || child > parallel) { fprintf(stderr, "bad -child\n"); return 2; }

    std::vector<const char*> tables;
    if (only) tables.push_back(only);
    else if (update > 0)
        for (const char* n : MAINT_TABLES) tables.push_back(n);
    else
        for (const char* n : SOURCE_TABLES) tables.push_back(n);

    for (const char* name : tables) {
        char path[1024];
        if (parallel > 1)
            snprintf(path, sizeof path, "%s/%s_%d_%d.dat", dir, name, child, parallel);
        else
            snprintf(path, sizeof path, "%s/%s.dat", dir, name);
        FILE* f = fopen(path, "w");
        if (!f) { fprintf(stderr, "cannot open %s\n", path); return 2; }
        generate_table(name, sf, parallel, child, update, f);
        fclose(f);
    }
    return 0;
}
