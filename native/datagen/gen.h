// Core definitions for the NDS-TPU native data generator.
//
// Counterpart of the reference's patched TPC-DS dsdgen C toolkit
// (reference nds/tpcds-gen/patches/code.patch + Makefile): same CLI
// semantics (-scale/-parallel/-child/-update, pipe-delimited output,
// per-chunk files) but an original counter-based design: every value is a
// pure function of (table, column, logical row index, scale), so any chunk
// of any table can be generated independently and the union over chunks is
// identical for every -parallel split. No shared state, no patching.
#pragma once

#include <cstdint>
#include <string>

enum ColKind { K_ID, K_ID64, K_INT, K_INT32, K_DEC, K_STR, K_DATE };

struct Col {
    const char* name;
    ColKind kind;
    int precision;
    int scale;
    int length;
    bool not_null;
};

struct TableDef {
    const char* name;
    const Col* cols;
    int ncols;
};

// ---------------------------------------------------------------------------
// counter-based RNG: splitmix64 over a (salt, stream, counter) key.
// Deterministic and O(1)-seekable — the property that makes -parallel/-child
// chunking exact (the reference toolkit instead re-seeds per chunk).
// ---------------------------------------------------------------------------
static inline uint64_t mix64(uint64_t x) {
    x += 0x9E3779B97F4A7C15ull;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
    return x ^ (x >> 31);
}

static inline uint64_t rng_at(uint64_t salt, uint64_t stream, uint64_t ctr) {
    return mix64(salt ^ mix64(stream ^ mix64(ctr)));
}

// uniform integer in [lo, hi] (inclusive)
static inline int64_t rng_range(uint64_t r, int64_t lo, int64_t hi) {
    if (hi <= lo) return lo;
    return lo + (int64_t)(r % (uint64_t)(hi - lo + 1));
}

static inline double rng_unit(uint64_t r) {
    return (double)(r >> 11) * (1.0 / 9007199254740992.0);  // 53-bit
}

// ---------------------------------------------------------------------------
// calendar: civil-date math. TPC-DS date surrogate keys are Julian day
// numbers; d_date_sk 2415022 == 1900-01-02 (first date_dim row).
// ---------------------------------------------------------------------------
static const int64_t JULIAN_1900_01_02 = 2415022;
static const int64_t DATE_DIM_ROWS = 73049;  // 1900-01-02 .. 2100-01-01

// days since civil epoch 1970-01-01 from y/m/d (Howard Hinnant's algorithm)
static inline int64_t days_from_civil(int y, int m, int d) {
    y -= m <= 2;
    const int era = (y >= 0 ? y : y - 399) / 400;
    const unsigned yoe = (unsigned)(y - era * 400);
    const unsigned doy = (unsigned)((153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1);
    const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
    return (int64_t)era * 146097 + (int64_t)doe - 719468;
}

struct Civil { int y, m, d; };

static inline Civil civil_from_days(int64_t z) {
    z += 719468;
    const int64_t era = (z >= 0 ? z : z - 146096) / 146097;
    const unsigned doe = (unsigned)(z - era * 146097);
    const unsigned yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
    const int64_t y = (int64_t)yoe + era * 400;
    const unsigned doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
    const unsigned mp = (5 * doy + 2) / 153;
    const unsigned d = doy - (153 * mp + 2) / 5 + 1;
    const unsigned m = mp + (mp < 10 ? 3 : -9);
    return {(int)(y + (m <= 2)), (int)m, (int)d};
}

// epoch-days (1970) of the first date_dim row
static const int64_t EPOCH_1900_01_02 = -25566;  // days_from_civil(1900,1,2)

static inline int64_t sk_to_epoch_days(int64_t date_sk) {
    return EPOCH_1900_01_02 + (date_sk - JULIAN_1900_01_02);
}
